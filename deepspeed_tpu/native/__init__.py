"""Native (C) runtime helpers, loaded via ctypes with pure-numpy fallbacks.

The reference's heavy host-side runtime work lives in C++ (torch DataLoader
workers, apex flatten/unflatten, the CUDA kernels).  The TPU compute path is
JAX/XLA/Pallas; this package carries the host-side native pieces — currently
the parallel batch-collation gather (``collate.c``).  The shared object is
compiled on first use with the system C compiler and cached; if no compiler
is available every entry point silently degrades to numpy.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "collate.c")
_LIB = None
_LOAD_TRIED = False


def _cache_dir() -> str:
    # A world-writable location (/tmp) would let another local user pre-plant
    # the .so and run code in this process; keep the cache private (0700) and
    # refuse to load anything we don't own.
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    if not os.path.isabs(base):
        # tempdir fallback is shared across users: keep per-uid isolation in
        # the name or the first user's 0700 dir locks everyone else out
        base = tempfile.gettempdir()
        d = os.path.join(base, f"deepspeed_tpu_{os.getuid()}")
    else:
        d = os.path.join(base, "deepspeed_tpu")
    os.makedirs(d, mode=0o700, exist_ok=True)
    os.chmod(d, 0o700)
    return d


def _so_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_cache_dir(), f"dstpu_collate_{digest}.so")


def _owned_by_us(path: str) -> bool:
    st = os.stat(path)
    return st.st_uid == os.getuid() and not (st.st_mode & 0o022)


def _load():
    """Compile (once, content-hashed cache) and dlopen the kernel."""
    global _LIB, _LOAD_TRIED
    if _LOAD_TRIED:
        return _LIB
    _LOAD_TRIED = True
    try:
        so = _so_path()   # inside try: collate.c may be absent (zip install)
        if not os.path.exists(so):
            cc = os.environ.get("CC", "cc")
            tmp = so + f".build{os.getpid()}"
            subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", "-pthread", "-o", tmp, _SRC],
                check=True, capture_output=True, timeout=60)
            os.chmod(tmp, 0o700)
            os.replace(tmp, so)        # atomic vs concurrent builders
        if not _owned_by_us(so):
            raise OSError(f"refusing to load {so}: not owned by uid "
                          f"{os.getuid()} with mode ~go-w")
        lib = ctypes.CDLL(so)
        lib.gather_rows.restype = ctypes.c_int
        lib.gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        _LIB = lib
    except Exception as e:  # no compiler / sandboxed tmp: numpy fallback
        logger.debug("native collate unavailable (%s); using numpy", e)
        _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


def gather_rows(src: np.ndarray, indices: np.ndarray,
                n_threads: Optional[int] = None) -> np.ndarray:
    """``src[indices]`` for a C-contiguous array with a leading sample axis,
    multithreaded memcpy when the native kernel is available (numpy fancy
    indexing is single-threaded), exact numpy fallback otherwise."""
    lib = _load()
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    if idx.ndim != 1:
        raise ValueError("indices must be 1-D")
    # identical index semantics on both paths: python wraparound for
    # negatives, bounds error otherwise
    n = src.shape[0] if src.ndim else 0
    if idx.size:
        idx = np.where(idx < 0, idx + n, idx)
        if idx.min() < 0 or idx.max() >= n:
            raise IndexError("gather index out of range")
    if lib is None or src.ndim == 0 or src.dtype.hasobject:
        # object dtype MUST take the numpy path: memcpy of PyObject*
        # without increfs corrupts refcounts
        return src[idx]
    out = np.empty((idx.size,) + src.shape[1:], dtype=src.dtype)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    if row_bytes == 0 or idx.size == 0:
        return out
    nt = n_threads or min(8, os.cpu_count() or 1)
    rc = lib.gather_rows(
        out.ctypes.data_as(ctypes.c_void_p),
        src.ctypes.data_as(ctypes.c_void_p),
        idx.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(idx.size), ctypes.c_int64(row_bytes),
        ctypes.c_int(nt))
    if rc != 0:  # pragma: no cover — kernel only returns 0
        return src[idx]
    return out
