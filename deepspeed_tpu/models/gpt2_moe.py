"""GPT-2 with Switch-style Mixture-of-Experts FFNs (expert parallelism).

Beyond-reference model family (see models/moe.py for the routing/expert
parallelism design): every block's FFN is a capacity-routed top-1 MoE, the
expert dim shards over the ``model`` axis, and the Switch load-balancing
aux loss joins the LM loss with ``aux_weight``.  A thin ``GPT2`` subclass:
only the block-stack hooks differ (init/specs/forward); embeddings, the
vocab-parallel head, and the engine protocol are inherited.
"""

from __future__ import annotations

import dataclasses

from deepspeed_tpu.models import moe as M
from deepspeed_tpu.models.gpt2 import GPT2, GPT2_SIZES


@dataclasses.dataclass
class GPT2MoE(GPT2):
    """Callable model object satisfying the engine protocol."""
    config: M.MoEConfig

    @classmethod
    def from_size(cls, size: str, num_experts: int = 8,
                  capacity_factor: float = 1.25, aux_weight: float = 0.01,
                  router_top_k: int = 1, **overrides) -> "GPT2MoE":
        kw = dict(GPT2_SIZES[size])
        kw.update(overrides)
        kw.setdefault("pre_ln", True)
        kw.setdefault("causal", True)
        return cls(M.MoEConfig(num_experts=num_experts,
                               capacity_factor=capacity_factor,
                               aux_weight=aux_weight,
                               router_top_k=router_top_k, **kw))

    def _init_blocks(self, rng):
        return M.init_moe_block_params(self.config, rng)

    def _block_specs(self):
        return M.moe_block_partition_specs()

    def _stack(self, x, blocks):
        x, aux = M.moe_stack_apply(x, blocks, self.config)
        return x, self.config.aux_weight * aux
