"""Native collation kernel + prefetching dataloader.

The reference data path rides torch's C++ DataLoader (worker processes,
C-side collation); the TPU-native equivalent is a ctypes-loaded pthreads
row-gather (deepspeed_tpu/native/collate.c) and a producer-thread prefetcher.
These tests pin exactness against numpy, the fallback path, batch identity
with and without prefetch, and engine integration.
"""

import numpy as np
import pytest

import jax
import deepspeed_tpu
from deepspeed_tpu import native
from deepspeed_tpu.data import ArrayDataset, DeepSpeedDataLoader


def test_native_kernel_compiles():
    # the test image ships cc; if this fails the fallback still works but
    # we want to KNOW the native path is exercised in CI
    assert native.available()


@pytest.mark.parametrize("shape,dtype", [
    ((64, 16), np.float32),
    ((64, 8, 4), np.float16),
    ((64,), np.int32),
    ((64, 33), np.int8),          # odd row size
])
def test_gather_matches_numpy(shape, dtype):
    rng = np.random.default_rng(0)
    src = (rng.normal(size=shape) * 10).astype(dtype)
    idx = rng.integers(0, shape[0], size=41)
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_gather_large_multithreaded():
    rng = np.random.default_rng(1)
    src = rng.normal(size=(4096, 512)).astype(np.float32)   # >1MB: threads
    idx = rng.permutation(4096)[:2048]
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_gather_bounds_checked():
    src = np.zeros((4, 2), np.float32)
    with pytest.raises(IndexError):
        native.gather_rows(src, np.asarray([0, 4]))


def test_numpy_fallback(monkeypatch):
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_LOAD_TRIED", True)
    src = np.arange(20, dtype=np.float32).reshape(10, 2)
    idx = np.asarray([3, 1, 7])
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def _collect(dl):
    return [jax.tree_util.tree_map(np.asarray, b) for b in dl]


def test_prefetch_same_batches():
    rng = np.random.default_rng(2)
    ds = ArrayDataset(rng.normal(size=(64, 8)).astype(np.float32),
                      rng.integers(0, 4, size=64).astype(np.int32))
    sync = DeepSpeedDataLoader(ds, batch_size=16, num_workers=0)
    pre = DeepSpeedDataLoader(ds, batch_size=16, num_workers=1)
    b1, b2 = _collect(sync), _collect(pre)
    assert len(b1) == len(b2) == 4
    for x, y in zip(b1, b2):
        for a, b in zip(jax.tree_util.tree_leaves(x),
                        jax.tree_util.tree_leaves(y)):
            np.testing.assert_array_equal(a, b)


def test_prefetch_early_break_stops_producer():
    """Abandoning iteration mid-epoch must release the producer thread (not
    leave it blocked on a full queue holding batches)."""
    import threading
    rng = np.random.default_rng(3)
    ds = ArrayDataset(rng.normal(size=(256, 8)).astype(np.float32))
    dl = DeepSpeedDataLoader(ds, batch_size=8, num_workers=1)
    it = iter(dl)
    next(it)
    it.close()   # what `break` + GC does deterministically
    import time
    deadline = time.time() + 5
    while time.time() < deadline:
        if not any(t.name == "dstpu-io-prefetch" and t.is_alive()
                   for t in threading.enumerate()):
            break
        time.sleep(0.05)
    assert not any(t.name == "dstpu-io-prefetch" and t.is_alive()
                   for t in threading.enumerate())


def test_gather_negative_indices_wraparound():
    src = np.arange(12, dtype=np.float32).reshape(6, 2)
    got = native.gather_rows(src, np.asarray([-1, 0, -6]))
    np.testing.assert_array_equal(got, src[[-1, 0, -6]])
    with pytest.raises(IndexError):
        native.gather_rows(src, np.asarray([-7]))


def test_prefetch_propagates_errors():
    class Broken:
        def __len__(self):
            return 32

        def __getitem__(self, i):
            if i > 10:
                raise RuntimeError("boom")
            return np.zeros((2,), np.float32)

    dl = DeepSpeedDataLoader(Broken(), batch_size=16, num_workers=1,
                             route="eval")
    with pytest.raises(RuntimeError, match="boom"):
        _collect(dl)


def test_engine_io_prefetch_trains():
    from simple_model import SimpleModel, random_dataset
    model = SimpleModel(16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 16,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10 ** 6},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    dl = engine.deepspeed_io(random_dataset(64, 16), num_local_io_workers=2)
    assert dl.num_workers == 2
    losses = []
    for batch in dl:
        loss = engine(*batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert len(losses) == 4 and all(np.isfinite(losses))
