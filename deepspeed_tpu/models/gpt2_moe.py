"""GPT-2 with Switch-style Mixture-of-Experts FFNs (expert parallelism).

Beyond-reference model family (see models/moe.py for the routing/expert
parallelism design): every block's FFN is a capacity-routed top-1 MoE, the
expert dim shards over the ``model`` axis, and the Switch load-balancing
aux loss joins the LM loss with ``aux_weight``.  Engine protocol identical
to ``GPT2`` — all parallelism/ZeRO/checkpoint subsystems compose via the
ordinary model-sharded leaf machinery.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import layers as L
from deepspeed_tpu.models import moe as M
from deepspeed_tpu.models.gpt2 import GPT2_SIZES
from deepspeed_tpu.parallel.topology import MODEL_AXIS


@dataclasses.dataclass
class GPT2MoE:
    """Callable model object satisfying the engine protocol."""
    config: M.MoEConfig

    @classmethod
    def from_size(cls, size: str, num_experts: int = 8,
                  capacity_factor: float = 1.25, aux_weight: float = 0.01,
                  **overrides) -> "GPT2MoE":
        kw = dict(GPT2_SIZES[size])
        kw.update(overrides)
        kw.setdefault("pre_ln", True)
        kw.setdefault("causal", True)
        return cls(M.MoEConfig(num_experts=num_experts,
                               capacity_factor=capacity_factor,
                               aux_weight=aux_weight, **kw))

    def validate(self, mp_size: int = 1):
        self.config.validate(mp_size)

    def init_params(self, rng):
        cfg = self.config
        cfg.validate()
        k_wte, k_wpe, k_blocks = jax.random.split(rng, 3)
        return {
            "wte": jax.random.normal(
                k_wte, (cfg.vocab_size, cfg.hidden_size), jnp.float32)
            * cfg.init_std,
            "wpe": jax.random.normal(
                k_wpe, (cfg.max_seq_len, cfg.hidden_size), jnp.float32)
            * cfg.init_std * 0.5,
            "blocks": M.init_moe_block_params(cfg, k_blocks),
            "lnf_s": jnp.ones((cfg.hidden_size,), jnp.float32),
            "lnf_b": jnp.zeros((cfg.hidden_size,), jnp.float32),
        }

    def partition_specs(self, params=None):
        return {
            "wte": P(MODEL_AXIS, None),
            "wpe": P(),
            "blocks": M.moe_block_partition_specs(),
            "lnf_s": P(), "lnf_b": P(),
        }

    def apply(self, params, tokens, labels):
        """Mean LM loss + aux_weight * Switch load-balance loss."""
        cfg = self.config
        T_len = tokens.shape[1]
        x = L.vocab_parallel_embedding(tokens, params["wte"])
        x = x + L.seq_shard_positions(params["wpe"], T_len).astype(
            x.dtype)[None]
        x, aux = M.moe_stack_apply(x, params["blocks"], cfg)
        x = L.layer_norm(x, params["lnf_s"], params["lnf_b"], cfg.ln_eps)
        logits = L.vocab_parallel_logits(x, params["wte"])
        loss = L.vocab_parallel_cross_entropy(logits, labels)
        lm = L.masked_mean_loss(loss, labels >= 0)
        return lm + cfg.aux_weight * aux

    __call__ = apply
