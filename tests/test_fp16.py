"""Engine + optimizer integration tests.

Behavioral equivalent of /root/reference/tests/unit/test_fp16.py: fp16
training paths for Adam/LAMB, ZeRO assertions, scheduler compatibility, and
the engine-level dynamic-loss-scale trajectories of
test_dynamic_loss_scale.py — all on the 8-fake-device CPU mesh.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfigError
from simple_model import (LinearSumModel, SimpleModel, args_from_dict,
                          random_dataset)

HIDDEN = 16


def base_config(**over):
    cfg = {
        "train_batch_size": 32,
        "steps_per_print": 100,
        "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
        "fp16": {"enabled": True, "initial_scale_power": 8},
    }
    cfg.update(over)
    return cfg


def run_training(model, config, steps=10, tmpdir=None, data_seed=0):
    args = args_from_dict(tmpdir, config) if tmpdir else None
    engine, optim, _, _ = deepspeed_tpu.initialize(
        args=args, config=None if tmpdir else config, model=model,
        model_parameters=model.init_params(None))
    ds = random_dataset(64, HIDDEN, seed=data_seed)
    dl = engine.deepspeed_io(ds)
    losses = []
    it = iter(dl)
    for _ in range(steps):
        try:
            batch = next(it)
        except StopIteration:
            it = iter(dl)
            batch = next(it)
        loss = engine(*batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return engine, optim, losses


def test_adam_fp16_basic(tmpdir):
    engine, optim, losses = run_training(SimpleModel(HIDDEN),
                                         base_config(), tmpdir=tmpdir)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert engine.global_steps == 10
    assert optim.cur_scale == 2 ** 8  # no overflow on sane data


def test_lamb_fp16_basic(tmpdir):
    cfg = base_config(optimizer={"type": "Lamb", "params": {"lr": 0.002}})
    engine, optim, losses = run_training(SimpleModel(HIDDEN), cfg,
                                         steps=20, tmpdir=tmpdir)
    assert all(np.isfinite(losses))
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_bf16_basic():
    cfg = base_config()
    del cfg["fp16"]
    cfg["bf16"] = {"enabled": True}
    engine, optim, losses = run_training(SimpleModel(HIDDEN), cfg)
    assert losses[-1] < losses[0]
    assert engine.params["w"].dtype == jnp.bfloat16


def test_fp32_basic():
    cfg = base_config()
    del cfg["fp16"]
    engine, optim, losses = run_training(SimpleModel(HIDDEN), cfg)
    assert losses[-1] < losses[0]
    assert engine.params["w"].dtype == jnp.float32


def test_unfused_optimizer_static_scale_unsupported():
    # reference: LAMB + static loss scale asserts (deepspeed_light.py:404-413)
    cfg = base_config(optimizer={"type": "Lamb", "params": {"lr": 0.01}},
                      fp16={"enabled": True, "loss_scale": 128})
    with pytest.raises(DeepSpeedConfigError):
        run_training(SimpleModel(HIDDEN), cfg, steps=1)


def test_zero_static_loss_scale(tmpdir):
    # reference test_fp16.py:253-279: ZeRO + static scale asserted
    cfg = base_config(zero_optimization=True,
                      fp16={"enabled": True, "loss_scale": 138.0})
    engine, optim, losses = run_training(SimpleModel(HIDDEN), cfg,
                                         tmpdir=tmpdir)
    assert optim.loss_scale == 138.0
    assert losses[-1] < losses[0]
    assert engine.zero_enabled


def test_zero_unsupported_optimizer_raises():
    # reference test_fp16.py:294-317 (assertion for untested optimizers)
    cfg = base_config(zero_optimization=True,
                      optimizer={"type": "Lamb", "params": {"lr": 0.01}})
    with pytest.raises(DeepSpeedConfigError):
        run_training(SimpleModel(HIDDEN), cfg, steps=1)


def test_zero_empty_partition():
    # reference test_fp16.py:320-347: more DP ranks than parameter elements;
    # with dp=8 a 2-element model leaves most partitions as pure padding
    model = LinearSumModel(dim=2)
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
        "fp16": {"enabled": True, "initial_scale_power": 8},
        "zero_optimization": True,
    }
    engine, optim, _ = run_training_linear(model, cfg, steps=3)
    assert engine.global_steps == 3


def run_training_linear(model, config, steps=3):
    engine, optim, _, _ = deepspeed_tpu.initialize(
        config=config, model=model, model_parameters=model.init_params(None))
    losses = []
    for i in range(steps):
        x = jnp.full((8, model.dim) if False else (8,), 0.1, jnp.float16)
        # batch over data axis: shape [8] -> one scalar element per rank
        loss = engine(x)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return engine, optim, losses


def test_zero_matches_non_zero():
    # same data, same seeds: ZeRO-1 partitioned Adam must track the replicated
    # Adam closely (fp32 master math is identical; reduction order differs)
    cfg_plain = base_config()
    cfg_zero = base_config(zero_optimization=True)
    m1 = SimpleModel(HIDDEN)
    m2 = SimpleModel(HIDDEN)
    e1, _, l1 = run_training(m1, cfg_plain, steps=5, data_seed=3)
    e2, _, l2 = run_training(m2, cfg_zero, steps=5, data_seed=3)
    np.testing.assert_allclose(l1, l2, rtol=2e-3)
    np.testing.assert_allclose(np.asarray(e1.master["w"], np.float32),
                               np.asarray(e2.params["w"], np.float32),
                               rtol=2e-2, atol=2e-4)


def test_scheduler_compat(tmpdir):
    # reference test_fp16.py:147-248: named schedulers drive the engine lr
    cfg = base_config(scheduler={
        "type": "WarmupLR",
        "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.01,
                   "warmup_num_steps": 4}})
    engine, optim, losses = run_training(SimpleModel(HIDDEN), cfg,
                                         steps=6, tmpdir=tmpdir)
    assert engine.lr_scheduler is not None
    # after >4 boundary steps lr reached warmup_max_lr
    assert optim.param_groups[0]["lr"] == pytest.approx(0.01)


def test_gradient_accumulation_equivalence():
    # gas=2 with half micro-batches must equal gas=1 on the same global batch
    cfg1 = {"train_batch_size": 16, "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
            "fp16": {"enabled": True, "initial_scale_power": 4}}
    cfg2 = {"train_batch_size": 16, "gradient_accumulation_steps": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
            "fp16": {"enabled": True, "initial_scale_power": 4}}
    ds = random_dataset(16, HIDDEN, seed=5)
    xs = np.stack([np.asarray(ds[i][0]) for i in range(16)])
    ys = np.stack([np.asarray(ds[i][1]) for i in range(16)])

    m = SimpleModel(HIDDEN)
    e1, _, _ = _engine(m, cfg1)
    loss = e1(jnp.asarray(xs), jnp.asarray(ys))
    e1.backward(loss)
    e1.step()

    e2, _, _ = _engine(m, cfg2)
    for half in (slice(0, 8), slice(8, 16)):
        loss = e2(jnp.asarray(xs[half]), jnp.asarray(ys[half]))
        e2.backward(loss)
        e2.step()

    assert e1.global_steps == 1 and e2.global_steps == 1
    np.testing.assert_allclose(np.asarray(e1.master["w"]),
                               np.asarray(e2.master["w"]), rtol=1e-3,
                               atol=1e-5)


def _engine(model, cfg):
    engine, optim, dl, sched = deepspeed_tpu.initialize(
        config=cfg, model=model, model_parameters=model.init_params(None))
    return engine, optim, dl


# ---------------------------------------------------------------- loss scale
# engine-level trajectories (reference test_dynamic_loss_scale.py)

def loss_scale_engine(initial_power=8, window=2, min_scale=1,
                      optimizer="Adam"):
    model = LinearSumModel(dim=8)
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": optimizer, "params": {"lr": 0.00015}},
        "fp16": {"enabled": True, "loss_scale": 0,
                 "initial_scale_power": initial_power,
                 "loss_scale_window": window,
                 "min_loss_scale": min_scale},
    }
    engine, optim, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model, model_parameters=model.init_params(None))
    return engine, optim


def run_model_step(engine, values):
    """Feed data whose gradient equals the value (inf/nan injection path)."""
    for v in values:
        x = jnp.full((8,), v, jnp.float32)
        loss = engine(x)
        engine.backward(loss)
        engine.step()


@pytest.mark.parametrize("optimizer", ["Adam", "Lamb"])
def test_engine_no_overflow(optimizer):
    engine, optim = loss_scale_engine(initial_power=8, window=2,
                                      optimizer=optimizer)
    expected_scale = 2 ** 8
    expected_window = 2
    assert optim.dynamic_loss_scale is True
    assert optim.cur_scale == expected_scale
    assert optim.scale_window == expected_window
    rng = np.random.default_rng(0)
    for i, value in enumerate(rng.uniform(-0.1, 0.1, 10)):
        run_model_step(engine, [value])
        assert optim.cur_scale == expected_scale
        assert optim.cur_iter == (i + 1)
        if optim.cur_iter % expected_window == 0:
            expected_scale *= 2


@pytest.mark.parametrize("optimizer", ["Adam", "Lamb"])
def test_engine_all_overflow(optimizer):
    engine, optim = loss_scale_engine(initial_power=4, window=2,
                                      min_scale=0.25, optimizer=optimizer)
    expected_scale = 2 ** 4
    assert optim.cur_scale == expected_scale
    overflow_values = [float("inf"), float("-inf")] + [float("nan")] * 6
    for i, value in enumerate(overflow_values):
        run_model_step(engine, [value])
        expected_scale = max(expected_scale / 2, 0.25)
        assert optim.cur_scale == expected_scale
        assert optim.cur_iter == (i + 1)
    assert engine.skipped_steps == len(overflow_values)


def test_engine_some_overflow():
    engine, optim = loss_scale_engine(initial_power=8, window=2)
    expected_scale = 2 ** 8
    expected_iteration = 0

    overflow_values = [float("inf"), float("nan")]
    expected_iteration += len(overflow_values)
    run_model_step(engine, overflow_values)
    expected_scale /= 2 ** len(overflow_values)
    assert optim.cur_scale == expected_scale
    assert optim.cur_iter == expected_iteration

    rng = np.random.default_rng(1)
    normal = rng.uniform(-0.1, 0.1, 3)  # window + 1
    expected_iteration += len(normal)
    run_model_step(engine, list(normal))
    expected_scale *= 2
    assert optim.cur_scale == expected_scale
    assert optim.cur_iter == expected_iteration

    run_model_step(engine, [float("inf")])
    expected_iteration += 1
    expected_scale /= 2
    assert optim.cur_scale == expected_scale
    assert optim.cur_iter == expected_iteration

    # params never absorbed a non-finite update
    assert np.all(np.isfinite(np.asarray(engine.master["w"])))
