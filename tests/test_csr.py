"""CSR algebra vs dense reference — port of
/root/reference/tests/unit/test_csr.py (addition with self and with a
different sparsity pattern), plus scale/allreduce helpers."""

import numpy as np

from deepspeed_tpu.sparse import CSRTensor, csr_allreduce


def random_row_sparse(rows=10, cols=5, seed=1234, p=0.25):
    rng = np.random.default_rng(seed)
    x = np.zeros((rows, cols), np.float32)
    x[0] = 1.0                     # first row always populated
    for i in range(1, rows):
        if rng.random() < p:
            x[i] = 1.0
    return x


def test_csr_addition_self():
    dense_x = random_row_sparse()
    cx = CSRTensor(dense_x)
    np.testing.assert_array_equal(np.asarray(cx.to_dense()), dense_x)
    cx.add(CSRTensor(dense_x))
    np.testing.assert_array_equal(np.asarray(cx.to_dense()),
                                  dense_x + dense_x)


def test_csr_addition_different():
    dense_x = random_row_sparse(seed=1)
    dense_y = random_row_sparse(seed=2)
    cx = CSRTensor(dense_x)
    cx.add(CSRTensor(dense_y))
    np.testing.assert_array_equal(np.asarray(cx.to_dense()),
                                  dense_x + dense_y)


def test_csr_empty():
    dense = np.zeros((4, 3), np.float32)
    c = CSRTensor(dense)
    np.testing.assert_array_equal(np.asarray(c.to_dense()), dense)
    nnz, total = c.sparse_size()
    assert nnz == 0 and total == 12


def test_csr_scale_and_sparse_size():
    dense = random_row_sparse(seed=7)
    c = CSRTensor(dense)
    np.testing.assert_allclose(np.asarray(c.scale(0.5).to_dense()),
                               dense * 0.5)
    nnz, total = c.sparse_size()
    assert total == dense.size
    assert nnz == int((dense.any(axis=1)).sum()) * dense.shape[1]


def test_csr_allreduce_matches_dense_mean():
    shards = [random_row_sparse(seed=s) for s in range(4)]
    got = np.asarray(csr_allreduce([CSRTensor(s) for s in shards]))
    want = np.mean(shards, axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-6)
