"""Synthetic heavy-traffic driver + serve telemetry.

The driver generates a deterministic request trace (seeded prompt/length
mix — the "millions of users" stand-in every serving bench and CI smoke
run replays identically), runs it through a scheduler, and reports the
serving headline numbers: tokens/s/chip and p50/p99 time-to-first-token
and inter-token latency.

Telemetry rides the PR 7/9 machinery unchanged, three event kinds on one
stream (``python -m deepspeed_tpu.observability`` validates all of them):

* ``dstpu.telemetry.serve`` v3 — one line per window of decode
  iterations, with live slot/page-pool gauges and latency percentiles
  derived from PER-REQUEST records (the old pooled per-token percentiles
  honestly collapsed to 0 under fused decode).
* ``dstpu.telemetry.request`` v1 — one line per COMPLETED request: the
  whole lifecycle (queue wait → prefill → decode → eviction) plus its
  prefix-reuse facts, emitted at eviction via the scheduler's
  ``on_complete`` hook.
* ``dstpu.telemetry.startup`` v2 — the cold-start record (restore
  latency + compile-cache counters), once at the first token.

The serve anomaly detectors run at each window flush; live endpoints and
the serve watchdog are
:class:`~deepspeed_tpu.inference.observability.ServeObservability`'s job
— :func:`run_serve` builds one automatically when the
``inference.observability`` config asks for it.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional

import jax
import numpy as np

from deepspeed_tpu.inference.scheduler import (ContinuousScheduler, Request,
                                               latency_samples_ms,
                                               latency_summary, percentile,
                                               request_latency_ms)

logger = logging.getLogger(__name__)


def synthetic_requests(n: int, *, vocab: int, seed: int = 0,
                       prompt_min: int = 4, prompt_max: int = 24,
                       new_min: int = 4, new_max: int = 24,
                       eos_id: Optional[int] = None) -> List[Request]:
    """Deterministic mixed-length trace: uniform prompt lengths and
    token budgets — the variance is what makes continuous batching win
    (uniform-length traffic would let static batching tie)."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.integers(prompt_min, prompt_max + 1))
        prompt = rng.integers(0, vocab, size=plen).astype(int).tolist()
        reqs.append(Request(
            rid=i, prompt=prompt,
            max_new_tokens=int(rng.integers(new_min, new_max + 1)),
            eos_id=eos_id))
    return reqs


class ServeTelemetry:
    """Windowed serve-event emitter: every ``window_iters`` scheduler
    iterations fold into one ``dstpu.telemetry.serve`` line (v3: live
    gauges + per-request-derived percentiles); each completed request
    emits one ``dstpu.telemetry.request`` line (``request_events``); the
    startup event goes out once, at the first token (when restore
    latency and the compile-cache counters are all known facts)."""

    def __init__(self, engine, jsonl_path: Optional[str] = None,
                 window_iters: Optional[int] = None,
                 request_events: Optional[bool] = None,
                 observability=None):
        cfg = engine.config
        if jsonl_path is None:
            jsonl_path = cfg.inference_obs_jsonl_path
        if window_iters is None:
            window_iters = cfg.inference_obs_window_iters
        if request_events is None:
            request_events = cfg.inference_obs_request_events
        if window_iters < 1:
            raise ValueError("window_iters must be >= 1")
        self.engine = engine
        self.window_iters = int(window_iters)
        self.request_events = bool(request_events)
        self.request_events_emitted = 0
        self.observability = observability
        self.sink = None
        if jsonl_path:
            from deepspeed_tpu.observability.registry import JsonlSink
            self.sink = JsonlSink(jsonl_path)
        # crash/exit post-mortems (DSTPU_FLIGHTREC_DUMP_AT_EXIT=1 in CI)
        # must work for a serving process exactly like a training one —
        # inference/observability.py owns the dump-dir resolution
        # (configured flight_recorder_dir beats the JSONL directory)
        from deepspeed_tpu.inference.observability import \
            configure_flight_recorder
        configure_flight_recorder(cfg, jsonl_path=jsonl_path)
        self._startup_emitted = False
        self._window = 0
        self._evicted_prev = 0
        self._gauges_prev = dict(engine.pool.gauges())
        self._spec_prev = (0, 0)
        self._reset_window()
        self.last_event = None

    def _reset_window(self):
        self._iters = 0
        self._tokens = 0
        self._admitted = 0
        self._active_sum = 0
        self._queue_depth = 0
        self._t0 = time.perf_counter()

    def _emit(self, event: dict):
        from deepspeed_tpu.observability import schema
        if event.get("schema") == schema.SERVE_SCHEMA_ID:
            # the endpoints' "last window" must be a WINDOW event —
            # request/startup lines share the stream but not the slot
            self.last_event = event
        if self.sink is not None:
            self.sink.emit(event)

    def on_iteration(self, sched, stats: dict):
        """Scheduler hook (``ContinuousScheduler(on_event=...)``)."""
        if not self._startup_emitted and self.engine.first_token_ts:
            self._startup_emitted = True
            self._emit(self.engine.startup_event())
        if self.observability is not None:
            self.observability.note_scheduler(sched)
        self._iters += 1
        self._tokens += stats["tokens_out"]
        self._admitted += stats["admitted"]
        self._active_sum += stats["active"]
        self._queue_depth = stats["queue_depth"]
        if self._iters >= self.window_iters:
            self.flush(sched)

    def on_complete(self, result) -> None:
        """Scheduler hook (``ContinuousScheduler(on_complete=...)``):
        one ``dstpu.telemetry.request`` line per completed request —
        the lifecycle record the summary percentiles are derived from,
        now also a queryable artifact.  Without a JSONL sink there is
        nowhere to write, so nothing is built or COUNTED — the
        summary's ``request_events`` must only claim lines that exist."""
        if not self.request_events or self.sink is None:
            return
        from deepspeed_tpu.observability import schema

        def ms(x):
            return None if x is None else round(x * 1e3, 4)

        itl = result.itl_s
        self.request_events_emitted += 1
        self._emit({
            "schema": schema.REQUEST_SCHEMA_ID,
            "version": schema.REQUEST_SCHEMA_VERSION,
            "ts": result.finished_ts or time.time(),
            "rid": int(result.rid),
            "slot": int(result.slot) if result.slot is not None else -1,
            "prompt_tokens": int(result.prompt_len),
            "tokens_out": len(result.tokens),
            "finish_reason": result.finish_reason,
            "queue_wait_ms": ms(result.queue_wait_s),
            "prefill_ms": ms(result.prefill_s),
            "ttft_ms": ms(result.ttft_s),
            "decode_ms": ms(result.decode_s),
            "itl_mean_ms": ms(result.itl_mean_s),
            "itl_max_ms": ms(max(itl)) if itl else None,
            "prefix_hit": bool(result.prefix_hit),
            "prefix_tokens_reused": int(result.reused_tokens),
            "pages_mapped": int(result.pages_mapped),
        })

    def flush(self, sched):
        """Emit the current (possibly partial) window; final partial
        windows are part of the record, like the training spool's."""
        if self._iters == 0:
            return
        from deepspeed_tpu.observability import detectors, schema
        from deepspeed_tpu.resilience import COUNTERS
        elapsed = time.perf_counter() - self._t0
        # percentiles over the run's completed PER-REQUEST records
        # (each request = one TTFT / mean-ITL / queue-wait sample —
        # meaningful at any decode_iters_per_dispatch; bench/CI traces
        # are bounded, a long-lived replica would swap in reservoir
        # sampling here to bound the per-window cost)
        ttft, itl_req, queue_wait = request_latency_ms(sched.results)
        _, itl_pooled = latency_samples_ms(sched.results)
        self._window += 1
        spec = self.engine.cache_spec
        from deepspeed_tpu.inference import kvcache
        gauges = self.engine.pool.gauges()
        counters = COUNTERS.as_dict()
        counters.update(detectors.SERVE_COUNTERS.as_dict())
        event = {
            "schema": schema.SERVE_SCHEMA_ID,
            "version": schema.SERVE_SCHEMA_VERSION,
            "ts": time.time(),
            "window": self._window,
            "decode_iters": self._iters,
            "tokens_out": self._tokens,
            "admitted": self._admitted,
            "evicted": sched.evicted,
            "active_slots_mean": round(self._active_sum
                                       / max(1, self._iters), 3),
            "queue_depth": self._queue_depth,
            "slots": spec.slots,
            "kv_cache_gb": round(kvcache.cache_bytes(spec) / 2 ** 30, 6),
            "tokens_per_sec": (round(self._tokens / elapsed, 3)
                               if elapsed > 0 else None),
            "ttft_p50_ms": percentile(ttft, 50),
            "ttft_p99_ms": percentile(ttft, 99),
            "itl_p50_ms": percentile(itl_req, 50),
            "itl_p99_ms": percentile(itl_req, 99),
            # ---- v2: prefix reuse + speculative decoding (cumulative
            # over the scheduler's lifetime, like `evicted`)
            "prefix_hits": int(getattr(sched, "prefix_hits", 0)),
            "prefix_tokens_reused": int(getattr(sched,
                                                "prefix_tokens_reused", 0)),
            "spec_proposed": int(getattr(sched, "spec_proposed", 0)),
            "spec_accepted": int(getattr(sched, "spec_accepted", 0)),
            # ---- v3: replica observability (live gauges + per-request
            # latency breakdowns; docs/observability.md "Serving view")
            "requests_completed": sched.evicted - self._evicted_prev,
            "queue_wait_p50_ms": percentile(queue_wait, 50),
            "queue_wait_p99_ms": percentile(queue_wait, 99),
            "itl_mean_ms": (round(float(np.mean(itl_pooled)), 4)
                            if itl_pooled else None),
            "slots_in_use": sched.active,
            "free_pages": gauges["free_pages"],
            "lru_pages": gauges["lru_pages"],
            "shared_pages": gauges["shared_pages"],
            "admission_refusals": int(getattr(sched,
                                              "admission_refusals", 0)),
            "counters": counters,
        }
        self._emit(event)
        self._evicted_prev = sched.evicted
        # serve anomaly detectors: window deltas of the pool/spec
        # counters (one-shot warnings + counters — the next window's
        # event carries the updated roll-up)
        if self.observability is not None:
            spec_prop = event["spec_proposed"]
            spec_acc = event["spec_accepted"]
            self.observability.detector.check_window(
                queue_depth=self._queue_depth,
                admitted=self._admitted,
                refusals_delta=(gauges["admission_refusals"]
                                - self._gauges_prev["admission_refusals"]),
                spec_proposed_delta=spec_prop - self._spec_prev[0],
                spec_accepted_delta=spec_acc - self._spec_prev[1],
                lru_reclaims_delta=(gauges["lru_reclaims"]
                                    - self._gauges_prev["lru_reclaims"]),
                prefix_hits_delta=(gauges["prefix_hits"]
                                   - self._gauges_prev["prefix_hits"]))
            self._spec_prev = (spec_prop, spec_acc)
        self._gauges_prev = gauges
        self._reset_window()

    def close(self):
        if self.sink is not None:
            self.sink.close()


def run_serve(engine, requests, *, jsonl_path: Optional[str] = None,
              window_iters: Optional[int] = None, sampler=None,
              observability=None) -> dict:
    """Run ``requests`` through continuous batching with telemetry;
    returns ``{"results", "summary"}`` where summary is
    :func:`~deepspeed_tpu.inference.scheduler.latency_summary` plus the
    scheduler's utilization counters.

    When the engine's ``inference.observability`` config enables a
    health port or a watchdog (and no ``observability`` driver was
    passed in), a :class:`~deepspeed_tpu.inference.observability.
    ServeObservability` is built for the run and closed with it.  A
    crash anywhere in the drain dumps the flight-recorder ring
    (``flightrec_rank<r>_crash.json``) before propagating — serving
    post-mortems ride the same hook as training ones."""
    from deepspeed_tpu.inference import observability as serve_obs
    from deepspeed_tpu.inference.scheduler import greedy_sampler
    from deepspeed_tpu.observability.flightrec import RECORDER
    obs, own_obs = observability, False
    if obs is None and serve_obs.configured(engine.config):
        obs = serve_obs.ServeObservability(engine)
        own_obs = True
    tel = ServeTelemetry(engine, jsonl_path=jsonl_path,
                         window_iters=window_iters, observability=obs)
    if obs is not None and obs.telemetry is None:
        obs.telemetry = tel
    sched = ContinuousScheduler(engine, sampler=sampler or greedy_sampler,
                                on_event=tel.on_iteration,
                                on_complete=tel.on_complete)
    if obs is not None:
        obs.note_scheduler(sched)
    t0 = time.perf_counter()
    try:
        results = sched.run(requests)
    except BaseException:
        # crash exit: leave the breadcrumb ring on disk so the
        # post-mortem names the admit/decode the replica died in —
        # best-effort, never masks the crash (the training driver's
        # contract, now shared by the serving path)
        RECORDER.record("crash", where="serve",
                        decode_iters=sched.decode_iters,
                        active=sched.active, queued=sched.pending)
        RECORDER.dump("crash")
        raise
    finally:
        if own_obs:
            obs.close()
    elapsed = time.perf_counter() - t0
    tel.flush(sched)
    tel.close()
    summary = latency_summary(results, elapsed,
                              n_chips=len(engine.mesh.devices.flat))
    prompt_tokens = sum(r.prompt_len for r in results)
    summary.update({
        "decode_iters": sched.decode_iters,
        "admitted": sched.admitted,
        "evicted": sched.evicted,
        "slots": engine.num_slots,
        "quantize": engine.quantize,
        "dtype": str(np.dtype(engine.compute_dtype)),
        "mp": engine.mp_world_size,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        # prefix reuse: hit rate over admissions, prompt tokens whose
        # prefill was served from shared pages instead of recomputed
        "prefix_hit_rate": (round(sched.prefix_hits
                                  / sched.admitted, 4)
                            if sched.admitted else None),
        "prefill_tokens_saved": sched.prefix_tokens_reused,
        "prefill_tokens_total": prompt_tokens,
        "admission_refusals": sched.admission_refusals,
        # speculative decoding: accepted draft proposals / proposed
        "spec_accept_rate": (round(sched.spec_accepted
                                   / sched.spec_proposed, 4)
                             if sched.spec_proposed else None),
        "spec_proposed": sched.spec_proposed,
        "spec_accepted": sched.spec_accepted,
        "draft_params": (_count_tree_params(engine.draft_params)
                         if engine.draft_params is not None else None),
        "request_events": tel.request_events_emitted,
    })
    return {"results": results, "summary": summary}


def _count_tree_params(tree) -> int:
    import jax as _jax
    leaves = _jax.tree_util.tree_leaves(tree)
    return int(sum(np.asarray(l).size for l in leaves))
