"""Host-concurrency analyzer (deepspeed_tpu/analysis/concurrency.py +
lockwatch.py, docs/analysis.md "Host concurrency").

The load-bearing pins:

* **Seeded defects are caught with file:line messages** — a lock-order
  inversion, an HTTP probe under a lock (the revert-twin of the PR 15
  ``_pick`` bug), and a cross-thread unlocked mutation each raise in
  error mode, and their fixed twins lint clean.
* **The shipped control plane is clean** — zero error-severity findings
  over the real router/scheduler/kvcache/observability/resilience
  modules (real findings were FIXED, not suppressed), so the CI
  ``concurrency-lint`` job gates on a true baseline.
* **The runtime sanitizer agrees with the static pass** — lockwatch's
  observed acquisition-order edges merge into the static graph without
  creating a cycle, its counters export through the registry shape, and
  long waits leave ``lock_wait`` flight-recorder breadcrumbs.
* **PagePool survives concurrent admit/evict/COW** — refcounts sum
  exactly and the free list never double-enters a page under scheduler
  threads with lockwatch armed.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu import analysis
from deepspeed_tpu.analysis import concurrency as conc
from deepspeed_tpu.analysis import lockwatch
from deepspeed_tpu.analysis import report as lint_report

pytestmark = pytest.mark.analysis


@pytest.fixture(autouse=True)
def _lockwatch_clean():
    """Every test starts disarmed with empty observation state."""
    lockwatch.instrument(False)
    lockwatch.reset()
    lockwatch.configure(wait_warn_ms=lockwatch.DEFAULT_WAIT_WARN_MS,
                        hold_warn_ms=lockwatch.DEFAULT_HOLD_WARN_MS)
    yield
    lockwatch.instrument(False)
    lockwatch.reset()
    lockwatch.configure(wait_warn_ms=lockwatch.DEFAULT_WAIT_WARN_MS,
                        hold_warn_ms=lockwatch.DEFAULT_HOLD_WARN_MS)


def _lint(tmp_path, source, name="mod_under_test.py"):
    path = tmp_path / name
    path.write_text(source)
    return conc.check_paths([str(path)]), str(path)


# ---------------------------------------------------------------------------
# seeded defect class 1: lock-order inversion
# ---------------------------------------------------------------------------

INVERSION = """\
import threading

class Pair:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()

    def forward(self):
        with self._lock_a:
            with self._lock_b:
                return 1

    def backward(self):
        with self._lock_b:
            with self._lock_a:
                return 2
"""


def test_lock_order_inversion_is_an_error(tmp_path):
    rep, path = _lint(tmp_path, INVERSION)
    errs = [f for f in rep.errors if f.code == "concurrency.lock-order"]
    assert errs, rep.format("info")
    msg = errs[0].message
    assert "Pair._lock_a" in msg and "Pair._lock_b" in msg
    # the cycle message names a concrete file:line edge site
    assert f"{path}:" in msg or (errs[0].source or "").startswith(path)


def test_lock_order_fixed_twin_is_clean(tmp_path):
    fixed = INVERSION.replace(
        "        with self._lock_b:\n            with self._lock_a:",
        "        with self._lock_a:\n            with self._lock_b:")
    rep, _ = _lint(tmp_path, fixed)
    assert not rep.errors and not rep.warnings, rep.format("info")


def test_self_deadlock_reacquire_is_an_error(tmp_path):
    rep, path = _lint(tmp_path, """\
import threading

class Once:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            return self.inner()

    def inner(self):
        with self._lock:
            return 1
""")
    errs = [f for f in rep.errors if f.code == "concurrency.lock-order"]
    assert errs and "self-deadlock" in errs[0].message
    # an RLock version is legal
    rep2, _ = _lint(tmp_path, """\
import threading

class Once:
    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            return self.inner()

    def inner(self):
        with self._lock:
            return 1
""", name="mod_rlock.py")
    assert not rep2.errors, rep2.format("info")


# ---------------------------------------------------------------------------
# seeded defect class 2: blocking under a lock (the PR 15 _pick twin)
# ---------------------------------------------------------------------------

HTTP_UNDER_LOCK = """\
import threading
import urllib.request

class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self.replicas = []

    def pick(self):
        with self._lock:
            for rep in self.replicas:
                urllib.request.urlopen(rep, timeout=2.0)
            return self.replicas[0] if self.replicas else None
"""


def test_http_probe_under_lock_is_an_error(tmp_path):
    rep, path = _lint(tmp_path, HTTP_UNDER_LOCK)
    errs = [f for f in rep.errors
            if f.code == "concurrency.blocking-under-lock"]
    assert errs, rep.format("info")
    assert "Router._lock" in errs[0].message
    # file:line in the source so the finding is actionable
    assert errs[0].source.startswith(f"{path}:12"), errs[0].source


def test_http_probe_outside_lock_is_clean(tmp_path):
    fixed = """\
import threading
import urllib.request

class Router:
    def __init__(self):
        self._lock = threading.Lock()
        self.replicas = []

    def pick(self):
        with self._lock:
            reps = list(self.replicas)
        for rep in reps:
            urllib.request.urlopen(rep, timeout=2.0)
        return reps[0] if reps else None
"""
    rep, _ = _lint(tmp_path, fixed)
    assert not rep.errors, rep.format("info")


def test_blocking_through_a_resolved_call_is_caught(tmp_path):
    rep, path = _lint(tmp_path, """\
import threading
import time

class Slow:
    def __init__(self):
        self._lock = threading.Lock()

    def nap_helper(self):
        time.sleep(1.0)

    def tick(self):
        with self._lock:
            self.nap_helper()
""")
    errs = [f for f in rep.errors
            if f.code == "concurrency.blocking-under-lock"]
    assert errs, rep.format("info")
    # the propagated finding names BOTH the call site and the sleep site
    assert "nap_helper" in errs[0].message
    assert "time.sleep" in errs[0].message


def test_allow_blocking_annotation_downgrades_to_info(tmp_path):
    allowed = HTTP_UNDER_LOCK.replace(
        "                urllib.request.urlopen(rep, timeout=2.0)",
        "                urllib.request.urlopen(rep, timeout=2.0)"
        "  # dstpu-lock: allow-blocking(test fixture)")
    rep, _ = _lint(tmp_path, allowed)
    assert not rep.errors, rep.format("info")
    assert any(f.code == "concurrency.allowed-blocking"
               for f in rep.infos)


# ---------------------------------------------------------------------------
# seeded defect class 3: cross-thread unlocked mutation
# ---------------------------------------------------------------------------

UNLOCKED_WRITE = """\
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def add(self, n):
        with self._lock:
            self.total += n

    def reset_fast(self):
        self.total = 0
"""


def test_unlocked_guarded_write_is_an_error(tmp_path):
    rep, path = _lint(tmp_path, UNLOCKED_WRITE)
    errs = [f for f in rep.errors
            if f.code == "concurrency.unlocked-guarded-write"]
    assert errs, rep.format("info")
    assert "total" in errs[0].message
    assert errs[0].source.startswith(f"{path}:13"), errs[0].source


def test_guarded_write_fixed_twin_is_clean(tmp_path):
    fixed = UNLOCKED_WRITE.replace(
        "    def reset_fast(self):\n        self.total = 0",
        "    def reset_fast(self):\n        with self._lock:\n"
        "            self.total = 0")
    rep, _ = _lint(tmp_path, fixed)
    assert not rep.errors, rep.format("info")


def test_init_annotated_function_is_exempt(tmp_path):
    rep, _ = _lint(tmp_path, UNLOCKED_WRITE.replace(
        "    def reset_fast(self):",
        "    # dstpu-thread: construction init\n"
        "    def reset_fast(self):"))
    assert not rep.errors, rep.format("info")


# ---------------------------------------------------------------------------
# thread-role contracts
# ---------------------------------------------------------------------------

def test_holds_contract_checks_callers(tmp_path):
    rep, _ = _lint(tmp_path, """\
import threading

class R:
    def __init__(self):
        self._lock = threading.Lock()
        self.m = {}

    # dstpu-thread: admission holds=R._lock
    def pick(self):
        self.m["k"] = 1
        return 1

    def good(self):
        with self._lock:
            return self.pick()

    def bad(self):
        return self.pick()
""")
    errs = [f for f in rep.errors
            if f.code == "concurrency.lock-contract"]
    assert len(errs) == 1, rep.format("info")
    assert "R.bad" in errs[0].source
    assert "holds=R._lock" in errs[0].message


def test_enqueue_only_rejects_blocking_and_locks(tmp_path):
    rep, _ = _lint(tmp_path, """\
import threading
import time

class Agg:
    def __init__(self):
        self._lock = threading.Lock()

    # dstpu-thread: drain-callback enqueue-only
    def publish(self, item):
        with self._lock:
            time.sleep(0.1)
""")
    codes = {f.code for f in rep.errors}
    assert "concurrency.thread-role" in codes, rep.format("info")
    roles = [f for f in rep.errors if f.code == "concurrency.thread-role"]
    msgs = " | ".join(f.message for f in roles)
    assert "enqueue-only" in msgs
    assert "acquires Agg._lock" in msgs


def test_owner_check_contract(tmp_path):
    rep, _ = _lint(tmp_path, """\
import threading

class R:
    def __init__(self):
        self._lock = threading.Lock()
        self.flights = {}

    # dstpu-thread: driver-callback owner-check=owner
    def complete(self, replica, rid):
        with self._lock:
            del self.flights[rid]
""")
    errs = [f for f in rep.errors if f.code == "concurrency.thread-role"]
    assert errs and "owner-check=owner" in errs[0].message
    rep2, _ = _lint(tmp_path, """\
import threading

class R:
    def __init__(self):
        self._lock = threading.Lock()
        self.flights = {}

    # dstpu-thread: driver-callback owner-check=owner
    def complete(self, replica, rid):
        with self._lock:
            f = self.flights.get(rid)
            if f is None or f.owner is not replica:
                return
            del self.flights[rid]
""", name="mod_owner_ok.py")
    assert not rep2.errors, rep2.format("info")


def test_dangling_annotation_is_a_warning(tmp_path):
    rep, _ = _lint(tmp_path, """\
import threading

X = 1
# dstpu-thread: orphan-role enqueue-only
Y = 2
""")
    assert any(f.code == "concurrency.annotation" for f in rep.warnings)


# ---------------------------------------------------------------------------
# the shipped control plane: clean, and gated
# ---------------------------------------------------------------------------

def test_shipped_control_plane_has_zero_findings():
    """The acceptance pin: real findings were FIXED (the router handoff
    unlink moved off the lock, PagePool grew its lock), not suppressed —
    so warn set AND error set are empty over the real modules."""
    rep = conc.check_paths()
    assert not rep.errors, rep.format("warning")
    assert not rep.warnings, rep.format("warning")


def test_static_model_covers_the_real_locks():
    model, rep = conc.analyze_paths(conc.control_plane_paths())
    names = set(model.locks)
    for expected in ("FleetRouter._lock", "PagePool._lock",
                     "MetricRegistry._lock", "FleetAggregator._lock",
                     "Watchdog._lock", "FlightRecorder._lock"):
        assert expected in names, sorted(names)
    # the shipped thread-role contracts are attached (not dangling)
    roles = set(model.roles)
    assert "router.FleetRouter._complete" in roles
    assert "router.FleetRouter._pick" in roles
    assert "fleet.FleetAggregator.publish" in roles


def test_error_mode_raises_concurrency_lint_error(tmp_path):
    rep, _ = _lint(tmp_path, INVERSION)
    with pytest.raises(analysis.ConcurrencyLintError) as ei:
        analysis.dispatch_report(rep, "error", where="test",
                                 label="concurrency lint",
                                 error_cls=conc.ConcurrencyLintError)
    assert "concurrency.lock-order" in str(ei.value)
    # warn mode only logs
    analysis.dispatch_report(rep, "warn", where="test",
                             label="concurrency lint",
                             error_cls=conc.ConcurrencyLintError)


def test_suppress_uses_report_prefix_semantics(tmp_path):
    path = tmp_path / "m.py"
    path.write_text(INVERSION)
    rep = conc.check_paths([str(path)],
                           suppress=["concurrency.lock-order"])
    assert not rep.errors
    assert rep.suppressed_count >= 1


def test_config_wires_analysis_concurrency():
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError

    def build(**analysis):
        return DeepSpeedConfig(
            {"train_batch_size": 4, "analysis": analysis}
            if analysis else {"train_batch_size": 4}, dp_world_size=1)

    c = build(concurrency="error")
    assert c.analysis_concurrency_mode == "error"
    c = build(concurrency={"mode": "warn",
                           "suppress": ["concurrency.lock-order"]})
    assert c.analysis_concurrency_mode == "warn"
    assert c.analysis_concurrency_suppress == ["concurrency.lock-order"]
    c = build()
    assert c.analysis_concurrency_mode == "off"
    with pytest.raises(DeepSpeedConfigError):
        build(concurrency={"oops": 1})
    with pytest.raises(DeepSpeedConfigError):
        build(concurrency="everything")


def test_cli_concurrency_error_mode_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    bad = tmp_path / "seeded.py"
    bad.write_text(HTTP_UNDER_LOCK)
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis", "--concurrency",
         "--concurrency-path", str(bad), "--mode", "error", "--json"],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 2, out.stderr
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["subject"] == "concurrency" and doc["errors"] >= 1
    codes = {f["code"] for f in doc["findings"]}
    assert "concurrency.blocking-under-lock" in codes
    # shipped modules: exit 0
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis", "--concurrency",
         "--mode", "error"],
        capture_output=True, text=True, env=env, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr


# ---------------------------------------------------------------------------
# shutdown ordering (the PR hardening of Replica.close)
# ---------------------------------------------------------------------------

def test_replica_close_joins_driver_before_endpoint_teardown():
    """Regression pin: close() must stop + JOIN the driver thread and
    only then tear down the observability endpoints — a driver mid-
    dispatch must never see its health server vanish under it."""
    from deepspeed_tpu.inference.router import Replica
    order = []
    rep = object.__new__(Replica)
    rep.stop = threading.Event()
    started = threading.Event()

    def drive():
        started.set()
        rep.stop.wait(timeout=10)
        time.sleep(0.05)
        order.append("driver-exit")

    rep.thread = threading.Thread(target=drive, daemon=True)

    class Obs:
        def close(self):
            order.append(("obs-close", rep.thread.is_alive()))

    rep.obs = Obs()
    rep.thread.start()
    assert started.wait(timeout=5)
    rep.close()
    assert rep.stop.is_set()
    assert order == ["driver-exit", ("obs-close", False)], order


def test_replica_close_from_its_own_driver_thread_does_not_join_self():
    from deepspeed_tpu.inference.router import Replica
    closed = threading.Event()
    rep = object.__new__(Replica)
    rep.stop = threading.Event()
    rep.obs = None

    def drive():
        rep.close()      # eviction path: the driver closes its replica
        closed.set()

    rep.thread = threading.Thread(target=drive, daemon=True)
    rep.thread.start()
    assert closed.wait(timeout=5), "close() deadlocked joining itself"
    rep.thread.join(timeout=5)


# ---------------------------------------------------------------------------
# lockwatch: the runtime half
# ---------------------------------------------------------------------------

def test_named_lock_plain_when_disarmed():
    lk = lockwatch.named_lock("T._lock")
    assert not isinstance(lk, lockwatch.InstrumentedLock)
    with lk:
        pass


def test_instrumented_lock_records_stats_and_edges():
    lockwatch.instrument(True)
    a = lockwatch.named_lock("T._a")
    b = lockwatch.named_lock("T._b")
    assert isinstance(a, lockwatch.InstrumentedLock)
    with a:
        with b:
            pass
    with a:
        pass
    snap = lockwatch.snapshot()
    assert snap["T._a"]["acquisitions"] == 2
    assert snap["T._b"]["acquisitions"] == 1
    assert ("T._a", "T._b") in lockwatch.observed_edges()
    assert ("T._b", "T._a") not in lockwatch.observed_edges()
    counters = lockwatch.counters()
    assert counters["lock_acquisitions.T._a"] == 2
    assert "lock_wait_ms.T._b" in counters
    assert "lock_held_ms.T._a" in counters


def test_instrumented_rlock_reentry_counts_once():
    lockwatch.instrument(True)
    lk = lockwatch.named_lock("T._r", rlock=True)
    with lk:
        with lk:
            assert lk.locked()
    assert not lk.locked()
    assert lockwatch.snapshot()["T._r"]["acquisitions"] == 1
    assert ("T._r", "T._r") not in lockwatch.observed_edges()


def test_contended_wait_leaves_a_flight_recorder_breadcrumb():
    from deepspeed_tpu.observability.flightrec import RECORDER
    lockwatch.instrument(True)
    lockwatch.configure(wait_warn_ms=1.0, hold_warn_ms=10_000.0)
    lk = lockwatch.named_lock("T._contended")
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            entered.set()
            release.wait(timeout=5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert entered.wait(timeout=5)
    waiter_done = threading.Event()
    rows = []

    def waiter():
        threading.Timer(0.05, release.set).start()
        with lk:
            pass
        waiter_done.set()

    w = threading.Thread(target=waiter, daemon=True, name="t-waiter")
    w.start()
    assert waiter_done.wait(timeout=5)
    t.join(timeout=5)
    rows = [r for r in RECORDER.tail(64)
            if r.get("kind") == "lock_wait"
            and r.get("lock") == "T._contended"]
    assert rows, "no lock_wait breadcrumb for the contended acquire"
    row = rows[-1]
    assert row["waiter"] == "t-waiter"
    assert row["wait_ms"] >= 1.0
    assert lockwatch.snapshot()["T._contended"]["contentions"] >= 1


def test_long_hold_leaves_a_lock_held_breadcrumb():
    from deepspeed_tpu.observability.flightrec import RECORDER
    lockwatch.instrument(True)
    lockwatch.configure(wait_warn_ms=10_000.0, hold_warn_ms=0.0)
    lk = lockwatch.named_lock("T._held")
    with lk:
        pass
    rows = [r for r in RECORDER.tail(64)
            if r.get("kind") == "lock_held"
            and r.get("lock") == "T._held"]
    assert rows and rows[-1]["held_ms"] >= 0.0


def test_register_metrics_exports_through_the_registry():
    from deepspeed_tpu.observability.registry import MetricRegistry
    lockwatch.instrument(True)
    lk = lockwatch.named_lock("T._m")
    with lk:
        pass
    reg = MetricRegistry()
    lockwatch.register_metrics(reg)
    snap = reg.collect()
    assert snap["lockwatch"]["lock_acquisitions.T._m"] == 1


def test_merge_observed_flags_a_runtime_only_inversion():
    model, _ = conc.analyze_paths(conc.control_plane_paths())
    # the static edges alone stay acyclic
    assert not conc.merge_observed(model, set()).errors
    # consistency contract: edges in the STATIC direction merge clean
    assert not conc.merge_observed(
        model, {("MetricSpool._lock", "MetricRegistry._lock")}).errors
    # a runtime edge OPPOSING a static edge is the deadlock the AST
    # could not prove — merge_observed must fail it
    rep = conc.merge_observed(
        model, {("MetricRegistry._lock", "MetricSpool._lock")})
    errs = [f for f in rep.errors if f.code == "concurrency.lock-order"]
    assert errs and "observed at runtime" in errs[0].message


# ---------------------------------------------------------------------------
# PagePool under concurrent threads with lockwatch armed
# ---------------------------------------------------------------------------

def test_pagepool_refcount_integrity_under_concurrency():
    from deepspeed_tpu.inference.kvcache import KVCacheSpec, PagePool
    lockwatch.instrument(True)
    spec = KVCacheSpec(layers=1, slots=8, capacity=64, kv_heads_local=1,
                       head_dim=8, page_tokens=8, pool_pages=48)
    pool = PagePool(spec)
    assert isinstance(pool._lock, lockwatch.InstrumentedLock)
    stop = threading.Event()
    failures = []

    def worker(slot, seed):
        rng = np.random.default_rng(seed)
        try:
            for _ in range(120):
                prompt = [int(x) for x in
                          rng.integers(0, 4, rng.integers(8, 33))]
                grant = pool.admit(slot, prompt,
                                   int(rng.integers(0, 16)))
                if grant is None:
                    continue
                if rng.random() < 0.5:
                    pool.publish(grant)
                pool.prepare_write(slot, range(len(prompt),
                                               len(prompt) + 4))
                pool.release(slot)
        except Exception as e:  # pragma: no cover - the failure signal
            failures.append(e)

    def reader():
        while not stop.is_set():
            g = pool.gauges()
            assert g["free_pages"] >= 0
            pool.rows()

    threads = [threading.Thread(target=worker, args=(s, 100 + s),
                                daemon=True) for s in range(spec.slots)]
    r = threading.Thread(target=reader, daemon=True)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    stop.set()
    r.join(timeout=10)
    assert not failures, failures
    assert not any(t.is_alive() for t in threads)

    # refcounts sum exactly: every page's refcount == the number of
    # slot allocations referencing it (all slots released -> all zero)
    counts = np.zeros_like(pool._ref)
    for alloc in pool._alloc:
        for page in alloc:
            counts[page] += 1
    assert np.array_equal(pool._ref, counts), (pool._ref, counts)
    assert int(pool._ref.sum()) == 0
    # no free-list double entry, and free/LRU/refcounted partition the
    # page space without overlap
    assert len(set(pool._free)) == len(pool._free)
    assert not (set(pool._free) & set(pool._lru))
    assert len(pool._free) + len(pool._lru) == spec.num_pages
    # the sanitizer actually watched: the pool lock has traffic, and the
    # observed order edges stay consistent with the static graph
    assert lockwatch.snapshot()["PagePool._lock"]["acquisitions"] > 0
    model, _ = conc.analyze_paths(conc.control_plane_paths())
    assert not conc.merge_observed(model,
                                   lockwatch.observed_edges()).errors


def test_pagepool_reset_preserves_the_lock():
    from deepspeed_tpu.inference.kvcache import KVCacheSpec, PagePool
    spec = KVCacheSpec(layers=1, slots=2, capacity=32, kv_heads_local=1,
                       head_dim=8, page_tokens=8)
    pool = PagePool(spec)
    lock_before = pool._lock
    grant = pool.admit(0, [1, 2, 3, 4, 5, 6, 7, 8, 9], 4)
    assert grant is not None
    pool.reset()
    assert pool._lock is lock_before
    assert len(pool._free) == spec.num_pages
    assert pool.admit(0, [1, 2, 3, 4, 5, 6, 7, 8, 9], 4) is not None
