"""Fused multi-head attention kernel (Pallas, TPU).

The XLA path in ``models/layers.py`` materialises the [B, n, T, T] fp32
score tensor in HBM twice per layer (scores write + softmax read) and again
in the backward replay — at BERT-large/seq128/batch96 that is ~300 MB of HBM
traffic per layer that never needed to leave the chip.  This kernel computes
QK^T → mask → softmax → ·V entirely in VMEM, one program per (batch row,
head block), with a custom-VJP backward that recomputes the probabilities in
VMEM and emits dQ/dK/dV in the same pass (the standard flash-attention
backward algebra; at the supported sequence lengths the whole [hb, T, T]
score tile fits on chip, so no online-softmax streaming is needed — longer
sequences fall back to the XLA path or ride the ring-attention sequence
axis).

Numerics: scores and probabilities are fp32 (max-subtracted softmax); the
probability·V contraction runs in the input dtype (bf16 on TPU) with fp32
accumulation — the same contract as the XLA path.

Use ``fused_attention(q, k, v, attn_mask, causal)`` with
``q/k/v: [B, T, n, d]`` and ``attn_mask: [B, T]`` float (1 = attend; pass
ones for none); callers gate on ``supported(...)``.  ``interpret=True`` runs
anywhere (CPU tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# fp32 score-tile budget per program; several such tiles are live in the
# backward kernel, so keep a healthy margin under the ~16 MB VMEM
SCORE_TILE_BUDGET = 2 * 1024 * 1024


def _head_block(n_heads: int) -> int:
    # blocks are [bb, hb, T, d]: Mosaic needs every block dim divisible by
    # (or equal to) the array dim; hb=8 keeps the score tile bounded for
    # many-head models
    return 8 if n_heads % 8 == 0 else n_heads


def _batch_block(B: int, T: int, hb: int, budget: int) -> int:
    # enough rows per program to amortise grid/DMA overhead (tiny per-head
    # programs are latency-bound), bounded by the score-tile budget
    for bb in (8, 4, 2, 1):
        if B % bb == 0 and bb * hb * T * T * 4 <= budget:
            return bb
    return 1


def supported(seq_len: int, n_heads: int, head_dim: int) -> bool:
    hb = _head_block(n_heads)
    # gate on the BACKWARD budget (half the forward's): even at bb=1 the
    # backward keeps p/dP/dS score tiles live, so a shape that only fits the
    # forward would exhaust VMEM on the grad pass
    return (seq_len % 8 == 0 and head_dim % 8 == 0
            and hb * seq_len * seq_len * 4 <= SCORE_TILE_BUDGET // 2)


def _fold(ref):
    """[bb, hb, T, d] block -> [bb*hb, T, d] (leading-dim reshape is free;
    Mosaic's matmul supports a single batch dim)."""
    bb, hb, T, d = ref.shape
    return ref[...].reshape(bb * hb, T, d)


def _scores(q, k, mask, causal, scale):
    """[bb*hb,T,d] x [bb*hb,T,d] (native dtype) -> masked fp32 [bb*hb,T,T]
    logits; ``mask`` is already expanded to [bb*hb, T]."""
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale
    T = q.shape[1]
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
        s = jnp.where((col <= row)[None], s, -1e9)
    s = jnp.where(mask[:, None, :] != 0, s, -1e9)
    return s


def _expand_mask(mask_ref, hb):
    """[bb, 1, T] mask block -> [bb*hb, T] row mask."""
    bb, _, T = mask_ref.shape
    m = jnp.broadcast_to(mask_ref[...], (bb, hb, T))
    return m.reshape(bb * hb, T)


def _softmax(s):
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    return e / jnp.sum(e, axis=-1, keepdims=True)


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, causal, scale):
    # blocks are [1, hb, T, d] in the heads-first layout: the batched dots
    # need NO in-VMEM transposes, and inputs stay in their native dtype —
    # the MXU accumulates in fp32 via preferred_element_type; an explicit
    # fp32 upcast would quarter the matmul rate
    bb, hb, T, d = q_ref.shape
    q = _fold(q_ref)
    k = _fold(k_ref)
    v = _fold(v_ref)
    p = _softmax(_scores(q, k, _expand_mask(mask_ref, hb), causal, scale))
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)            # [bb*hb, T, d]
    o_ref[...] = o.reshape(bb, hb, T, d).astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref,
                dq_ref, dk_ref, dv_ref, *, causal, scale):
    bb, hb, T, d = q_ref.shape
    q = _fold(q_ref)
    k = _fold(k_ref)
    v = _fold(v_ref)
    do = _fold(do_ref)
    cdt = q.dtype
    p = _softmax(_scores(q, k, _expand_mask(mask_ref, hb), causal, scale))
    pc = p.astype(cdt)
    bdims = ((0,), (0,))
    # dV = P^T dO   (contract over the query axis, batched)
    dv = jax.lax.dot_general(pc, do, (((1,), (1,)), bdims),
                             preferred_element_type=jnp.float32)
    # dP = dO V^T
    dp = jax.lax.dot_general(do, v, (((2,), (2,)), bdims),
                             preferred_element_type=jnp.float32)
    # dS = P ∘ (dP − rowsum(dP ∘ P)) ; the scale folds into dQ/dK
    ds = (p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))).astype(cdt)
    dq = jax.lax.dot_general(ds, k, (((2,), (1,)), bdims),
                             preferred_element_type=jnp.float32) * scale
    dk = jax.lax.dot_general(ds, q, (((1,), (1,)), bdims),
                             preferred_element_type=jnp.float32) * scale
    dq_ref[...] = dq.reshape(bb, hb, T, d).astype(dq_ref.dtype)
    dk_ref[...] = dk.reshape(bb, hb, T, d).astype(dk_ref.dtype)
    dv_ref[...] = dv.reshape(bb, hb, T, d).astype(dv_ref.dtype)


def _specs(B, T, n, d, bwd=False):
    hb = _head_block(n)
    # the backward keeps ~2x more score-sized tiles live (p, dP, dS)
    bb = _batch_block(B, T, hb,
                      SCORE_TILE_BUDGET // (2 if bwd else 1))
    # kernel layout is heads-first [B, n, T, d] (the public API transposes
    # on the XLA side, where the copy fuses with the qkv slice)
    qkv = pl.BlockSpec((bb, hb, T, d), lambda i, j: (i, j, 0, 0))
    # mask rides as [B, 1, T] so the trailing block dims are (1, T)
    mask = pl.BlockSpec((bb, 1, T), lambda i, j: (i, 0, 0))
    return qkv, mask, (B // bb, n // hb)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_attention(q, k, v, attn_mask, causal: bool = False,
                    interpret: bool = False):
    """q/k/v: [B, T, n, d]; attn_mask: [B, T] float (1 = attend) — pass
    ``jnp.ones`` for none.  Returns [B, T, n, d] context."""
    return _fwd(q, k, v, attn_mask, causal, interpret)


def _hf(x):
    """public [B, T, n, d] -> kernel [B, n, T, d] (XLA-side transpose)."""
    return jnp.moveaxis(x, 2, 1)


def _fwd(q, k, v, attn_mask, causal, interpret):
    B, T, n, d = q.shape
    qkv_spec, mask_spec, grid = _specs(B, T, n, d)
    scale = 1.0 / (d ** 0.5)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, scale=scale),
        out_shape=jax.ShapeDtypeStruct((B, n, T, d), q.dtype),
        grid=grid,
        in_specs=[qkv_spec, qkv_spec, qkv_spec, mask_spec],
        out_specs=qkv_spec,
        interpret=interpret,
    )(_hf(q), _hf(k), _hf(v), attn_mask[:, None, :])
    return jnp.moveaxis(out, 1, 2)


def _fused_fwd(q, k, v, attn_mask, causal, interpret):
    return _fwd(q, k, v, attn_mask, causal, interpret), (q, k, v, attn_mask)


def _fused_bwd(causal, interpret, res, g):
    q, k, v, attn_mask = res
    B, T, n, d = q.shape
    qkv_spec, mask_spec, grid = _specs(B, T, n, d, bwd=True)
    scale = 1.0 / (d ** 0.5)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, causal=causal, scale=scale),
        out_shape=(jax.ShapeDtypeStruct((B, n, T, d), q.dtype),
                   jax.ShapeDtypeStruct((B, n, T, d), k.dtype),
                   jax.ShapeDtypeStruct((B, n, T, d), v.dtype)),
        grid=grid,
        in_specs=[qkv_spec, qkv_spec, qkv_spec, mask_spec, qkv_spec],
        out_specs=(qkv_spec, qkv_spec, qkv_spec),
        interpret=interpret,
    )(_hf(q), _hf(k), _hf(v), attn_mask[:, None, :], _hf(g))
    # mask is a float selector, not a trainable input
    return (jnp.moveaxis(dq, 1, 2), jnp.moveaxis(dk, 1, 2),
            jnp.moveaxis(dv, 1, 2), jnp.zeros_like(attn_mask))


fused_attention.defvjp(_fused_fwd, _fused_bwd)
