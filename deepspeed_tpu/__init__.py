"""deepspeed_tpu — a TPU-native training engine with the capabilities of
DeepSpeed v0.1.0 (and beyond: ZeRO stages 1-3 including stage-3/FSDP
parameter partitioning, pipeline GPipe/1F1B, sequence/context parallelism
two ways — ring attention and Ulysses all-to-all — and MoE expert
parallelism), built on JAX / XLA / Pallas / pjit.

Public API mirrors the reference (/root/reference/deepspeed/__init__.py:28-169):
``initialize(...)`` returns an ``(engine, optimizer, dataloader, lr_scheduler)``
4-tuple; ``add_config_arguments(parser)`` injects the standard CLI flags.
Submodules: ``models`` (sharded GPT-2/BERT family incl. ring/Ulysses
attention), ``tokenization`` + ``squad`` (wordpiece pipeline),
``metrics``, ``checkpoint`` (incl. ``load_module_tree``/
``init_from_module_tree`` transfer), ``ops`` (optimizers incl. Lion +
Pallas kernels), ``parallel`` (mesh/collectives/pipeline), ``zero3``
(parameter-partitioning helpers), ``resilience`` (preemption-safe
training, auto-resume, hang watchdog, fault injection —
docs/resilience.md).
"""

from deepspeed_tpu import compat as _compat  # noqa: F401  (installs jax shims)

__version__ = "0.1.0"
__version_major__, __version_minor__, __version_patch__ = (
    int(x) for x in __version__.split("."))
__git_hash__ = None
__git_branch__ = None


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mesh=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               param_groups=None,
               seed=0):
    """Construct the engine; returns (engine, optimizer, dataloader, lr_scheduler).

    Reference signature: /root/reference/deepspeed/__init__.py:28-102.  The
    ``mpu`` argument becomes ``mesh`` (a ``jax.sharding.Mesh`` or a
    ``deepspeed_tpu.parallel.MeshConfig``); ``model`` is a model-returning-loss
    callable or a ``deepspeed_tpu.Module``; ``model_parameters`` is the initial
    parameter pytree (or None to let the module init them).
    """
    from deepspeed_tpu.engine import DeepSpeedTpuEngine

    engine = DeepSpeedTpuEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mesh=mesh,
                                dist_init_required=dist_init_required,
                                collate_fn=collate_fn,
                                config=config,
                                config_params=config_params,
                                param_groups=param_groups,
                                seed=seed)
    return_items = [engine,
                    engine.optimizer,
                    engine.training_dataloader,
                    engine.lr_scheduler]
    return tuple(return_items)


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, use_mpi=False):
    """Early multi-host rendezvous — MUST run before any other JAX call on
    multi-host launches (jax.distributed requirement).  The engine also
    triggers this from its ctor, but user scripts that touch JAX before
    ``initialize()`` (e.g. to init model params) should call this first.
    Reference analog: dist.init_process_group, deepspeed_light.py:125-130."""
    from deepspeed_tpu.parallel.topology import init_distributed as _init
    _init(coordinator_address=coordinator_address,
          num_processes=num_processes, process_id=process_id,
          use_mpi=use_mpi)


def _add_core_arguments(parser):
    """Core flags (reference /root/reference/deepspeed/__init__.py:105-153)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag for user code, no impact on engine)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed json configuration file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated enable DeepSpeed (helper flag for user code)")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated path to DeepSpeed json configuration")
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Run via MPI; rank/size discovered from the MPI environment")
    return parser


def add_config_arguments(parser):
    """Update an argument parser to enable config-file params
    (reference /root/reference/deepspeed/__init__.py:156-169)."""
    parser = _add_core_arguments(parser)
    return parser
