"""Device mesh construction and multi-host bootstrap.

TPU-native replacement for the reference's distributed-backend plumbing:

* ``torch.distributed.init_process_group('nccl')`` + env:// rendezvous
  (/root/reference/deepspeed/pt/deepspeed_light.py:125-130) becomes
  ``jax.distributed.initialize(coordinator, num_processes, process_id)``.
* The ``mpu`` protocol (get_model/data_parallel_rank/group/world_size, see
  docs/_pages/features.md §"Support for Custom Model Parallelism") becomes a
  2-D ``jax.sharding.Mesh`` with named axes ``('data', 'model')``: the mesh
  *is* the mpu.  Tensor-parallel degree = size of the ``model`` axis; data
  parallelism (and ZeRO-1 partitioning) ride the ``data`` axis.
* ``_mpi_check`` rank discovery (/root/reference/deepspeed/pt/
  deepspeed_light.py:187-223) becomes env-var discovery of OMPI/PMI vars —
  no mpi4py needed for rendezvous, matching the reference's "MPI for
  discovery, not data" stance.

Mesh axis order is (data, model): with the model axis innermost/minor,
tensor-parallel collectives map onto the fastest ICI links while DP gradient
reductions ride the remaining dimensions — same reasoning as the reference
putting NCCL rings within a node for MP.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

DATA_AXIS = "data"
PIPE_AXIS = "pipe"    # pipeline parallelism (layer stages, GPipe schedule)
SEQ_AXIS = "seq"      # context/sequence parallelism (ring attention)
MODEL_AXIS = "model"

_ACTIVE_MESH: Optional[Mesh] = None


@dataclasses.dataclass
class MeshConfig:
    """Declarative mesh request: model_parallel_size chips per model replica,
    context_parallel_size chips per sequence ring,
    pipeline_parallel_size chips per layer pipeline, the rest of the slice
    becomes the data axis."""
    model_parallel_size: int = 1
    context_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    devices: Optional[Sequence] = None  # default: all visible devices


def make_mesh(model_parallel_size: int = 1,
              context_parallel_size: int = 1,
              devices: Optional[Sequence] = None,
              pipeline_parallel_size: int = 1) -> Mesh:
    """Build the global ('data', 'pipe', 'seq', 'model') mesh.

    The equivalent of constructing DP/MP process groups (reference
    deepspeed_light.py:63-77 and the Megatron mpu) plus context- and
    pipeline-parallel axes the reference lacks (SURVEY.md §2.3 row 22):
    devices are laid out [data, pipe, seq, model] with model innermost so
    tensor-parallel collectives ride the fastest ICI links, the sequence
    ring next (ppermute neighbours adjacent), the pipeline ring outside
    that (stage handoffs are one activation per tick — latency-tolerant),
    and DP gradient reductions across the remaining dimension.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    mp = int(model_parallel_size)
    sp = int(context_parallel_size)
    pp = int(pipeline_parallel_size)
    if mp < 1 or sp < 1 or pp < 1 or n % (mp * sp * pp) != 0:
        raise ValueError(
            f"model_parallel_size {mp} x context_parallel_size {sp} x "
            f"pipeline_parallel_size {pp} must divide device count {n}")
    dp = n // (mp * sp * pp)
    arr = np.asarray(devices).reshape(dp, pp, sp, mp)
    return Mesh(arr, (DATA_AXIS, PIPE_AXIS, SEQ_AXIS, MODEL_AXIS))


def set_mesh(mesh: Mesh) -> None:
    global _ACTIVE_MESH
    _ACTIVE_MESH = mesh


def get_mesh() -> Optional[Mesh]:
    return _ACTIVE_MESH


def data_parallel_size(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]


def model_parallel_size(mesh: Mesh) -> int:
    return mesh.shape[MODEL_AXIS]


def context_parallel_size(mesh: Mesh) -> int:
    return mesh.shape.get(SEQ_AXIS, 1)


def pipeline_parallel_size(mesh: Mesh) -> int:
    return mesh.shape.get(PIPE_AXIS, 1)


# ------------------------------------------------------------------ bootstrap

def mpi_discovery() -> dict:
    """Discover rank/world/coordinator from an MPI/PMI launch environment.

    Parity with ``_mpi_check`` (reference deepspeed_light.py:187-223), which
    uses mpi4py to find rank/size/master then exports RANK/WORLD_SIZE/
    MASTER_ADDR/MASTER_PORT.  Process-per-host on TPU, so local_rank is 0.
    """
    def _first_env(*names, default=None):
        for nm in names:
            if nm in os.environ:
                return os.environ[nm]
        return default

    rank = _first_env("OMPI_COMM_WORLD_RANK", "PMI_RANK", "SLURM_PROCID")
    size = _first_env("OMPI_COMM_WORLD_SIZE", "PMI_SIZE", "SLURM_NTASKS")
    if rank is None or size is None:
        raise RuntimeError(
            "MPI discovery requested but no OMPI/PMI/SLURM rank variables found")
    master_addr = _first_env("MASTER_ADDR", default="127.0.0.1")
    master_port = _first_env("MASTER_PORT", default="29500")
    return {
        "rank": int(rank),
        "world_size": int(size),
        "coordinator_address": f"{master_addr}:{master_port}",
    }


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     use_mpi: bool = False) -> None:
    """Multi-host rendezvous.

    Replaces ``dist.init_process_group`` (reference deepspeed_light.py:125-130).
    Env-var contract mirrors the launcher's: the launcher exports
    ``DSTPU_COORDINATOR``, ``DSTPU_NUM_PROCESSES``, ``DSTPU_PROCESS_ID``
    (analogous to MASTER_ADDR/WORLD_SIZE/RANK, reference
    deepspeed_launch.py:92-106).  Single-process runs skip initialization.
    """
    explicit_coordinator = coordinator_address is not None
    if use_mpi:
        info = mpi_discovery()
        coordinator_address = coordinator_address or info["coordinator_address"]
        num_processes = num_processes if num_processes is not None else info["world_size"]
        process_id = process_id if process_id is not None else info["rank"]

    coordinator_address = coordinator_address or os.environ.get("DSTPU_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("DSTPU_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("DSTPU_PROCESS_ID", "0"))

    if jax.distributed.is_initialized():
        # already rendezvoused (e.g. the user called init_distributed before
        # constructing the engine, whose ctor re-runs it off the env
        # contract) — a second jax.distributed.initialize would raise
        logger.info("init_distributed: already initialized, skipping")
        return

    if num_processes <= 1 and not explicit_coordinator:
        # nothing to rendezvous — covers launcher-spawned 1-process runs that
        # export DSTPU_COORDINATOR (jax.distributed.initialize would fail if
        # the XLA backend is already up).  An EXPLICITLY passed coordinator
        # still rendezvouses: the caller asked for it, and skipping would
        # silently split a multi-host job into isolated worlds.
        logger.info("init_distributed: single-process run, skipping rendezvous")
        return

    platforms = [p.strip() for p in
                 os.environ.get("JAX_PLATFORMS", "").split(",") if p.strip()]
    if not platforms or "cpu" in platforms:
        # multi-process runs on the CPU backend need a real collectives
        # implementation; without it every cross-process psum fails with
        # "Multiprocess computations aren't implemented on the CPU
        # backend".  Covers the explicit JAX_PLATFORMS=cpu case (the
        # distributed test tier) AND the unset case, where jax may
        # auto-select CPU on accelerator-less hosts — the flag only
        # configures the CPU client, so it is inert when an accelerator
        # wins the auto-selection.  (Backend auto-detection cannot be
        # queried here: touching it would initialize XLA before the
        # rendezvous below.)
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            # gloo multiplexes every collective of a pair over one TCP
            # connection; concurrent in-flight collectives from the CPU
            # backend's async dispatch interleave frames on it and die
            # with "op.preamble.length <= op.nbytes".  Serialize dispatch
            # on multi-process CPU — a correctness switch for CI rigs,
            # where CPU throughput is irrelevant.
            jax.config.update("jax_cpu_enable_async_dispatch", False)
            logger.info("init_distributed: gloo CPU collectives enabled "
                        "(async dispatch off)")
        except Exception as e:  # option renamed/absent on this jax
            logger.warning(
                "init_distributed: could not select gloo CPU collectives "
                "(%s) — multi-process CPU collectives may be unavailable", e)

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info("init_distributed: process %d/%d via %s",
                process_id, num_processes, coordinator_address)
