"""The four graph-lint passes.

Each pass takes a traced jaxpr (open or closed) and appends
:class:`~deepspeed_tpu.analysis.report.Finding`s to a
:class:`~deepspeed_tpu.analysis.report.Report`.  See docs/analysis.md for
the rule catalogue; rule codes are stable and suppressible by prefix.

1. ``collectives``  — every rank must issue the same ordered collective
   sequence.  Under SPMD the one divergence mechanism is control flow on a
   rank-dependent value, so the pass taints dataflow from ``axis_index`` and
   compares the ordered collective signatures of every ``cond``/``switch``
   branch whose predicate carries that taint (the 1F1B/GPipe stage
   schedules in parallel/pipeline.py are exactly this shape).  Signatures
   include the operand shape/dtype — the wire format — so the
   ``overlap_comm`` bucketed boundary (K same-primitive collectives told
   apart only by their bucket shapes) and the ZeRO-3 prefetched gather
   sequence compare exactly: branches bucketing the same payload
   differently are a real deadlock and are flagged.  Also checks
   axis names against the engine mesh and ``ppermute`` permutation validity
   — all of ``comm.py``'s wrappers (psum, psum_scatter with
   ``axis_index_groups`` sub-groups, all_gather) produce these primitives.
2. ``precision``    — fp32 compute reachable from low-precision values via
   an explicit upcast.  The error class is a convert-to-fp32 feeding a
   ``dot_general``/conv (doubles MXU and HBM cost versus a bf16 dot with
   ``preferred_element_type=fp32``, which is free and is NOT flagged);
   large elementwise upcast islands are reported at info, low-precision
   big reductions at warning.
3. ``transfers``    — in-graph host round trips (``pure_callback`` /
   ``io_callback``), weak-typed program inputs (Python scalars in carried
   state force a retrace when their dtype promotes), and donation
   opportunities (a large input whose shape/dtype matches an output and is
   not in ``donated_invars``).
4. ``shard specs``  — shard_map/NamedSharding PartitionSpecs validated
   against the mesh and the actual values BEFORE compile: unknown axes,
   specs longer than the value rank, and non-divisible dims.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.analysis import graph as G
from deepspeed_tpu.analysis import report as R

# primitive-name sets ------------------------------------------------------

#: blocking cross-rank primitives (mismatched order across ranks = deadlock)
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "pmean", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "pgather",
    "psum_invariant",
})

#: in-graph host round trips; pure/io callbacks stall the device every step
HARD_CALLBACK_PRIMS = frozenset({"pure_callback", "io_callback",
                                 "outside_call", "host_callback_call"})
SOFT_CALLBACK_PRIMS = frozenset({"debug_callback", "debug_print"})

DOT_PRIMS = frozenset({"dot_general", "conv_general_dilated"})
REDUCE_PRIMS = frozenset({"reduce_sum", "cumsum", "cumlogsumexp"})

LOW_PRECISION = (jnp.bfloat16, jnp.float16)

#: element-count thresholds: below these an upcast / low-precision reduce is
#: noise (scalars, layer-norm stats), above it it is load-bearing
UPCAST_INFO_MIN_SIZE = 1 << 16
LOWP_REDUCE_MIN_SIZE = 1 << 16
DONATION_MIN_BYTES = 1 << 20


def _is_lowp(dtype) -> bool:
    return dtype is not None and any(dtype == jnp.dtype(d)
                                     for d in LOW_PRECISION)


def _is_f32(dtype) -> bool:
    return dtype is not None and dtype == jnp.dtype(jnp.float32)


# ======================================================================
# Pass 1: collective consistency
# ======================================================================

#: operand-independent layout params that change the wire format of a
#: collective (all_to_all split/concat dims, scatter tiling): two ranks
#: issuing the "same" collective with different layouts still mismatch
_SIG_LAYOUT_KEYS = ("split_axis", "concat_axis", "split_count",
                    "scatter_dimension", "all_gather_dimension", "tiled",
                    "axis")


def _collective_sig(eqn) -> Tuple:
    p = eqn.params
    axes = p.get("axes", p.get("axis_name", ()))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    groups = p.get("axis_index_groups")
    perm = p.get("perm")
    layout = tuple((k, p[k]) for k in _SIG_LAYOUT_KEYS if k in p)
    # operand shapes/dtypes are part of the wire format: under overlap_comm
    # the boundary issues K same-primitive bucketed collectives whose only
    # distinguishing feature is the buffer shape, so two branches bucketing
    # the same payload DIFFERENTLY (or one bucketed, one monolithic) must
    # compare unequal — ranks in either branch would block exchanging
    # mismatched buffers.  ALL operands are hashed: psum-family eqns carry
    # several arrays at once, and a divergence in operand 2..N (or in the
    # operand count) mismatches on the wire just as hard as the first
    op = tuple(
        (tuple(getattr(v.aval, "shape", ())),
         str(getattr(v.aval, "dtype", "")))
        for v in eqn.invars)
    return (
        eqn.primitive.name,
        tuple(str(a) for a in axes),
        None if groups is None else tuple(tuple(g) for g in groups),
        None if perm is None else tuple(tuple(pr) for pr in perm),
        layout,
        op,
    )


def _fmt_sig(sig: Tuple) -> str:
    if sig[0] == "scan":           # composite: ("scan", length, inner_sigs)
        _, length, inner = sig
        body = ", ".join(_fmt_sig(s) for s in inner)
        return f"scan[length={length}]({body})"
    name, axes, groups, perm, layout, op = sig
    s = f"{name}(axis={','.join(axes)}"
    if groups is not None:
        s += f", groups={list(map(list, groups))}"
    if perm is not None:
        s += f", perm={list(map(list, perm))}"
    for k, v in layout:
        s += f", {k}={v}"
    for shape, dt in op:
        s += f", operand={dt}{list(shape)}"
    return s + ")"


def _first_divergence(a: List[Tuple], b: List[Tuple]) -> str:
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            return (f"position {i}: {_fmt_sig(x)} vs {_fmt_sig(y)}")
    if len(a) != len(b):
        i = min(len(a), len(b))
        longer = a if len(a) > len(b) else b
        return (f"position {i}: {_fmt_sig(longer[i])} vs <no collective> "
                f"(sequence lengths {len(a)} vs {len(b)})")
    return "<identical>"


#: full-axis sum/max-style reductions whose result is REPLICATED over the
#: reduced axes (without axis_index_groups) — they launder rank identity
RANK_INVARIANT_PRIMS = frozenset({"psum", "pmax", "pmin", "pmean",
                                  "all_gather", "psum_invariant"})


def check_collectives(jaxpr, report: R.Report,
                      mesh_axes: Optional[Sequence[str]] = None) -> None:
    """Pass 1.  ``mesh_axes``: the engine mesh axis names; None skips the
    axis-name check (standalone jaxprs traced with axis_env)."""
    known_axes = set(map(str, mesh_axes)) if mesh_axes is not None else None

    def visit(j, taint: G.AxisTaint, path: str) -> List[Tuple]:
        seq: List[Tuple] = []
        jj = G._as_open_jaxpr(j)
        if jj is None:
            return seq
        for eqn in jj.eqns:
            name = eqn.primitive.name
            if name == "axis_index":
                ax = eqn.params.get("axis_name")
                axs = ax if isinstance(ax, (tuple, list)) else (ax,)
                for v in eqn.outvars:
                    taint.mark(v, tuple(str(a) for a in axs))
            elif (name in RANK_INVARIANT_PRIMS
                    and eqn.params.get("axis_index_groups") is None):
                # a full-axis reduce/gather replicates its result over the
                # reduced axes: rank-dependence over THOSE axes ends here
                sig_axes = _collective_sig(eqn)[1]
                taint.step(eqn, removed=sig_axes)
            else:
                taint.step(eqn)

            if name in COLLECTIVE_PRIMS:
                sig = _collective_sig(eqn)
                seq.append(sig)
                if known_axes is not None:
                    unknown = [a for a in sig[1] if a not in known_axes]
                    if unknown:
                        report.add(
                            "collective.axis-unknown", R.ERROR,
                            f"{_fmt_sig(sig)} reduces over axis "
                            f"{unknown} which is not an engine mesh axis "
                            f"{sorted(known_axes)}; this program cannot run "
                            f"on the engine mesh",
                            path=path, source=G.source_of(eqn),
                            pass_name="collectives")
                if sig[3] is not None:      # ppermute perm validity
                    srcs = [p[0] for p in sig[3]]
                    dsts = [p[1] for p in sig[3]]
                    if len(set(srcs)) != len(srcs) or \
                            len(set(dsts)) != len(dsts):
                        report.add(
                            "collective.ppermute-malformed", R.ERROR,
                            f"{_fmt_sig(sig)} has duplicate sources or "
                            f"destinations: it is not a permutation, so "
                            f"some rank will wait on a message that never "
                            f"arrives (deadlock)",
                            path=path, source=G.source_of(eqn),
                            pass_name="collectives")

            subs = G.subjaxprs(eqn)
            if not subs:
                continue

            if name in ("cond", "switch") and len(subs) > 1:
                pred = eqn.invars[0]
                pred_rankdep = bool(taint.axes_of(pred))
                branch_seqs = []
                for i, (label, sub) in enumerate(subs):
                    sub_path = f"{path}/{label}" if path else label
                    sub_t = taint.seed_sub(eqn, sub)
                    branch_seqs.append(visit(sub, sub_t, sub_path))
                    taint.propagate_out(eqn, sub, sub_t)
                base = branch_seqs[0]
                mismatch = next((i for i, b in enumerate(branch_seqs[1:], 1)
                                 if b != base), None)
                if mismatch is not None:
                    detail = _first_divergence(base,
                                               branch_seqs[mismatch])
                    if pred_rankdep:
                        report.add(
                            "collective.divergent-order", R.ERROR,
                            f"cond/switch branches issue DIFFERENT ordered "
                            f"collective sequences and the predicate "
                            f"depends on axis_index (rank identity): ranks "
                            f"taking different branches will block in "
                            f"mismatched collectives — a whole-slice "
                            f"deadlock at run time.  First divergence: "
                            f"{detail}",
                            path=path, source=G.source_of(eqn),
                            pass_name="collectives")
                    else:
                        report.add(
                            "collective.branch-mismatch", R.INFO,
                            f"cond/switch branches issue different "
                            f"collective sequences ({detail}); safe only "
                            f"if the predicate is identical on every rank "
                            f"— verify it derives from replicated state",
                            path=path, source=G.source_of(eqn),
                            pass_name="collectives")
                # representative branch for the enclosing sequence
                seq.extend(base)
            else:
                for label, sub in subs:
                    sub_path = f"{path}/{label}" if path else label
                    sub_t = taint.seed_sub(eqn, sub)
                    sub_seq = visit(sub, sub_t, sub_path)
                    taint.propagate_out(eqn, sub, sub_t)
                    if name == "scan" and sub_seq:
                        # fold the trip count into the signature: a scan
                        # issues its body's collectives `length` times, so
                        # branches scanning the same body DIFFERENT numbers
                        # of times must compare unequal (a real deadlock),
                        # and the length is visible in the report
                        seq.append(("scan", eqn.params.get("length"),
                                    tuple(sub_seq)))
                    else:
                        seq.extend(sub_seq)
        return seq

    visit(jaxpr, G.AxisTaint(), "")


# ======================================================================
# Pass 2: precision flow
# ======================================================================

def check_precision(jaxpr, report: R.Report) -> None:
    """Pass 2: upcast-then-dot errors, large upcast islands, low-precision
    reductions.  The taint is "was explicitly converted up from bf16/fp16":
    converting back down to a low-precision dtype launders it (layer-norm /
    gelu fp32 islands end in a down-cast and stay quiet unless a dot ran
    inside)."""

    def visit(j, upcast: G.Taint, path: str, emit: bool = True) -> None:
        jj = G._as_open_jaxpr(j)
        if jj is None:
            return
        for eqn in jj.eqns:
            name = eqn.primitive.name
            if name == "convert_element_type":
                new_dtype = jnp.dtype(eqn.params.get("new_dtype"))
                src = eqn.invars[0]
                if _is_f32(new_dtype) and _is_lowp(G.dtype_of(src)):
                    for v in eqn.outvars:
                        upcast.mark(v)
                    if emit and G.size_of(src) >= UPCAST_INFO_MIN_SIZE:
                        report.add(
                            "precision.upcast", R.INFO,
                            f"large fp32 upcast of a "
                            f"{G.dtype_of(src)} value "
                            f"({G.size_of(src)} elements): fp32 copies "
                            f"double HBM traffic; intended for loss / "
                            f"norm islands, a mistake on the compute path",
                            path=path, source=G.source_of(eqn),
                            pass_name="precision")
                elif _is_lowp(new_dtype):
                    # down-cast launders the upcast taint
                    pass
                else:
                    upcast.step(eqn)
                continue

            if emit and name in DOT_PRIMS:
                out_dt = G.dtype_of(eqn.outvars[0])
                if _is_f32(out_dt) and upcast.any_marked(eqn.invars):
                    report.add(
                        "precision.upcast-dot", R.ERROR,
                        "fp32 matmul/conv on operands explicitly upcast "
                        "from bf16/fp16: this runs the MXU at fp32 rates "
                        "and doubles operand HBM bytes.  Keep the operands "
                        "low-precision and request fp32 accumulation via "
                        "preferred_element_type=jnp.float32 instead",
                        path=path, source=G.source_of(eqn),
                        pass_name="precision")

            if emit and name in REDUCE_PRIMS:
                in_dt = G.dtype_of(eqn.invars[0])
                if _is_lowp(in_dt) and \
                        G.size_of(eqn.invars[0]) >= LOWP_REDUCE_MIN_SIZE:
                    # info, not warning: the biggest legitimate source is
                    # the transpose of broadcast-adds (bias grads), which
                    # every fp16 framework sums in compute dtype under the
                    # loss-scale FSM's protection.  Forward-path bf16 sums
                    # are worth a look, hence the report.
                    report.add(
                        "precision.lowp-accum", R.INFO,
                        f"{name} accumulates {G.size_of(eqn.invars[0])} "
                        f"elements in {in_dt}: large sums lose mantissa "
                        f"bits in bf16/fp16 — if this is forward-path "
                        f"compute (not a bias-grad transpose), accumulate "
                        f"in fp32 and down-cast the result",
                        path=path, source=G.source_of(eqn),
                        pass_name="precision")

            subs = G.subjaxprs(eqn)
            if subs:
                # sub-jaxpr-carrying equations propagate through the
                # bodies ONLY (seed -> visit -> propagate_out): a coarse
                # outer step would re-taint outputs whose branches all
                # laundered the upcast with a down-cast
                for label, sub in subs:
                    sub_path = f"{path}/{label}" if path else label
                    sub_t = upcast.seed_sub(eqn, sub)
                    if name == "scan":
                        # loop-carried taint: an upcast created in
                        # iteration N can reach a dot in iteration N+1
                        # through the carry, so iterate taint-only passes
                        # (emit=False) mapping carry-out -> carry-in to a
                        # fixed point before the reporting pass
                        _scan_carry_fixpoint(eqn, sub, sub_t, sub_path)
                    visit(sub, sub_t, sub_path, emit=emit)
                    upcast.propagate_out(eqn, sub, sub_t)
            else:
                # taint flows through everything else (stopped only by
                # the explicit down-cast branch above)
                upcast.step(eqn)

    def _scan_carry_fixpoint(eqn, sub, sub_t, sub_path):
        body = G._as_open_jaxpr(sub)
        num_consts = int(eqn.params.get("num_consts", 0))
        num_carry = int(eqn.params.get("num_carry", 0))
        if num_carry <= 0 or body is None:
            return
        carry_in = body.invars[num_consts:num_consts + num_carry]
        carry_out = body.outvars[:num_carry]
        for _ in range(num_carry + 1):      # monotone; small bound suffices
            visit(sub, sub_t, sub_path, emit=False)
            changed = False
            for co, ci in zip(carry_out, carry_in):
                if sub_t.is_marked(co) and not sub_t.is_marked(ci):
                    sub_t.mark(ci)
                    changed = True
            if not changed:
                return

    visit(jaxpr, G.Taint(), "")


# ======================================================================
# Pass 3: transfers / recompilation
# ======================================================================

def _is_spool_drain(eqn) -> bool:
    """Allowlist check: the telemetry drain callback carries a
    ``_dstpu_spool_drain`` marker on the wrapped host function
    (observability/spool.py sets it on the one function it passes to
    ``io_callback``).  Matching on the marker — not the primitive — means
    any OTHER io_callback in a step program still errors."""
    cb = eqn.params.get("callback")
    if cb is None:
        return False
    fn = getattr(cb, "callback_func", None) or getattr(cb, "f", None) or cb
    return bool(getattr(fn, "_dstpu_spool_drain", False))


def check_transfers(jaxpr, report: R.Report) -> None:
    """Pass 3: host callbacks, weak-typed inputs, donation opportunities.
    The telemetry spool's once-per-window drain callback is allowlisted
    (``transfer.spool-drain``, info) — see :func:`_is_spool_drain`."""
    jj = G._as_open_jaxpr(jaxpr)
    if jj is None:
        return

    # weak-typed program inputs: a Python scalar in carried state retraces
    # the program when its value becomes a strong-typed array
    for i, v in enumerate(jj.invars):
        aval = G.aval_of(v)
        if getattr(aval, "weak_type", False):
            report.add(
                "transfer.weak-type", R.WARNING,
                f"program input {i} is weak-typed ({aval}): it was traced "
                f"from a Python scalar — passing a jnp/np array (or a "
                f"different Python type) later forces a silent retrace "
                f"and recompile.  Stage carried state as jnp.asarray with "
                f"an explicit dtype",
                path="", source="", pass_name="transfers")

    for eqn, path in G.walk(jj):
        name = eqn.primitive.name
        if name in HARD_CALLBACK_PRIMS:
            if _is_spool_drain(eqn):
                # the ONE sanctioned ordered host transfer: the telemetry
                # MetricSpool's batched drain callback — dispatched once
                # per report window (never per step), reading a tiny ring
                # buffer the compiled step filled on device
                # (observability/spool.py).  An UNSPOOLED per-step
                # io_callback still takes the error branch below.
                report.add(
                    "transfer.spool-drain", R.INFO,
                    f"{name} is the telemetry MetricSpool drain — an "
                    f"allowlisted ordered host transfer batched once per "
                    f"report window (docs/observability.md)",
                    path=path, source=G.source_of(eqn),
                    pass_name="transfers")
                continue
            report.add(
                "transfer.host-callback", R.ERROR,
                f"{name} embeds a host round trip in the step program: "
                f"the device blocks on Python every execution — on a pod "
                f"slice every chip stalls for the slowest host.  Move the "
                f"computation into the graph or do it outside the step",
                path=path, source=G.source_of(eqn), pass_name="transfers")
        elif name in SOFT_CALLBACK_PRIMS:
            report.add(
                "transfer.debug-callback", R.WARNING,
                f"{name} (jax.debug.*) runs a host callback inside the "
                f"step program; fine for debugging, remove before "
                f"production runs",
                path=path, source=G.source_of(eqn), pass_name="transfers")

        # donation: a pjit level records donated_invars; large inputs whose
        # aval matches an output and are not donated double-buffer in HBM
        if name == "pjit" and "donated_invars" in eqn.params:
            donated = eqn.params["donated_invars"]
            sub = G._as_open_jaxpr(eqn.params.get("jaxpr"))
            if sub is None:
                continue
            out_avals = {}
            for ov in sub.outvars:
                aval = G.aval_of(ov)
                key = (getattr(aval, "shape", None),
                       str(getattr(aval, "dtype", "")))
                out_avals[key] = out_avals.get(key, 0) + 1
            for i, (iv, don) in enumerate(zip(sub.invars, donated)):
                if don:
                    continue
                aval = G.aval_of(iv)
                key = (getattr(aval, "shape", None),
                       str(getattr(aval, "dtype", "")))
                nbytes = G.size_of(iv) * getattr(
                    getattr(aval, "dtype", np.dtype(np.int8)), "itemsize", 1)
                if out_avals.get(key, 0) > 0 and \
                        nbytes >= DONATION_MIN_BYTES:
                    out_avals[key] -= 1
                    report.add(
                        "transfer.donation", R.INFO,
                        f"input {i} ({key[0]}, {key[1]}, "
                        f"{nbytes / 2**20:.1f} MiB) matches an output "
                        f"shape/dtype but is not donated: XLA keeps both "
                        f"buffers live across the step.  If the caller "
                        f"does not reuse it, donate it "
                        f"(jax.jit(..., donate_argnums=...))",
                        path=path, source=G.source_of(eqn),
                        pass_name="transfers")


# ======================================================================
# Pass 4: shard-spec validation
# ======================================================================

def _spec_entries(spec):
    """PartitionSpec -> list of per-dim entries (each None | str | tuple)."""
    return list(spec)


def _axes_of_entry(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(str(a) for a in entry)
    return (str(entry),)


def check_shard_specs(mesh_shape, specs, tree, report: R.Report,
                      where: str = "") -> None:
    """Pass 4: validate a pytree of PartitionSpecs against the mesh and the
    matching pytree of values/ShapeDtypeStructs.  ``mesh_shape`` is a
    ``{axis_name: size}`` mapping (``dict(mesh.shape)``).  Findings carry
    the pytree path so the error names the offending leaf."""
    mesh_shape = dict(mesh_shape)
    flat_s, _ = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    flat_v, _ = jax.tree_util.tree_flatten_with_path(tree)
    vals = [(jax.tree_util.keystr(p), v) for p, v in flat_v]
    for pth, spec in flat_s:
        if not isinstance(spec, jax.sharding.PartitionSpec):
            continue
        key = jax.tree_util.keystr(pth)
        entries = _spec_entries(spec)
        spec_label = f"{where}{key}" if where else (key or "<root>")
        for axis in {a for e in entries for a in _axes_of_entry(e)}:
            if axis not in mesh_shape:
                report.add(
                    "shardspec.axis-unknown", R.ERROR,
                    f"{spec_label}: spec {spec} names mesh axis {axis!r} "
                    f"but the engine mesh has axes "
                    f"{sorted(mesh_shape)}",
                    path=spec_label, pass_name="shard-specs")
        # a spec pytree may be a PREFIX of the value pytree (one spec for
        # a whole subtree — valid shard_map in_specs): the spec applies
        # to EVERY value leaf under its path, so validate against all of
        # them, not just an exact path match
        leaves = [(kv, v) for kv, v in vals
                  if kv == key or kv.startswith(key)]
        for leaf_key, leaf in leaves:
            shape = getattr(leaf, "shape", None)
            if shape is None:
                continue
            label = f"{where}{leaf_key}" if where else (leaf_key or "<leaf>")
            if len(entries) > len(shape):
                report.add(
                    "shardspec.rank", R.ERROR,
                    f"{label}: spec {spec} has {len(entries)} entries but "
                    f"the value has rank {len(shape)} "
                    f"(shape {tuple(shape)})",
                    path=label, pass_name="shard-specs")
                continue
            for dim, entry in enumerate(entries):
                axes = [a for a in _axes_of_entry(entry) if a in mesh_shape]
                if not axes:
                    continue
                total = 1
                for a in axes:
                    total *= int(mesh_shape[a])
                if total > 0 and shape[dim] % total != 0:
                    report.add(
                        "shardspec.indivisible", R.ERROR,
                        f"{label}: dim {dim} of shape {tuple(shape)} is "
                        f"sharded over axis "
                        f"{entry!r} (size {total}) by spec {spec}, but "
                        f"{shape[dim]} % {total} != 0 — shard_map would "
                        f"fail or silently pad.  Fix the batch/param "
                        f"shape or the spec",
                        path=label, pass_name="shard-specs")
