"""Chunked checkpoint container + async saves + stage-3 native sharding.

Round-5 checkpoint scale-honesty work (VERDICT r4 weak #3, ADVICE r4
medium): files are streamed per-leaf through the DSTPUCK1 container
(write RAM = one leaf), readers get memmap views, stage-3 saves write
per-(row, dp) shard files instead of materialising full leaves on every
host, and saves can run on a background writer thread with only the
device→host snapshot stalling training.
"""

import os
import pickle
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import checkpoint as ckpt_mod
from deepspeed_tpu.models import GPT2

pytestmark = pytest.mark.slow

VOCAB, SEQ = 64, 16


def tiny_gpt2():
    return GPT2.from_size("tiny", vocab_size=VOCAB, max_seq_len=SEQ,
                          num_layers=2, hidden_size=32, num_heads=4)


def make_engine(stage=0, seed=7, **cfg_over):
    cfg = {
        "train_batch_size": 8,
        "steps_per_print": 10 ** 6,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
    }
    if stage:
        cfg["zero_optimization"] = {"stage": stage}
    cfg.update(cfg_over)
    model = tiny_gpt2()
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(seed)))
    return engine


def lm_batch(seed=1):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, VOCAB, size=(8, SEQ)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    labels[:, -1] = -1
    return toks, labels


# ------------------------------------------------------------- container

def test_container_roundtrip(tmp_path):
    import ml_dtypes
    p = str(tmp_path / "obj.pt")
    obj = {
        "big": np.arange(4096, dtype=np.float32).reshape(64, 64),
        "bf16": np.ones((128, 3), ml_dtypes.bfloat16),
        "small": np.float32(3.5),           # stays inline
        "zerod": np.asarray(7, np.int32),
        "nested": {"t": (np.full((300,), 2.0), "str", 11, None)},
        "list": [np.arange(600, dtype=np.int64)],
    }
    ckpt_mod._save_obj(p, obj)
    with open(p, "rb") as f:
        assert f.read(8) == ckpt_mod._MAGIC
    got = ckpt_mod._load_obj(p)
    np.testing.assert_array_equal(np.asarray(got["big"]), obj["big"])
    np.testing.assert_array_equal(
        np.asarray(got["bf16"]).astype(np.float32), np.ones((128, 3)))
    assert float(got["small"]) == 3.5 and int(got["zerod"]) == 7
    np.testing.assert_array_equal(np.asarray(got["nested"]["t"][0]),
                                  obj["nested"]["t"][0])
    assert got["nested"]["t"][1:] == ("str", 11, None)
    # chunks come back as read-only memmap views (restores stream)
    assert isinstance(got["big"], np.memmap)


def test_legacy_plain_pickle_still_loads(tmp_path):
    # round <= 4 files are a single restricted pickle with no magic
    p = str(tmp_path / "legacy.pt")
    obj = {"module": {"w": np.arange(10, dtype=np.float32)},
           "global_steps": 3}
    with open(p, "wb") as f:
        pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)
    got = ckpt_mod._load_obj(p)
    np.testing.assert_array_equal(got["module"]["w"], obj["module"]["w"])
    assert got["global_steps"] == 3


def test_container_rejects_forbidden_globals(tmp_path):
    p = str(tmp_path / "evil.pt")
    w = ckpt_mod._ChunkedWriter(p)
    w.finish({"x": 1})
    # craft a malicious header in an otherwise valid container
    import io

    class Evil:
        def __reduce__(self):
            return (os.system, ("true",))

    with open(p, "r+b") as f:
        f.seek(0, io.SEEK_END)
        off = f.tell()
        pickle.dump({"boom": Evil()}, f)
        f.seek(len(ckpt_mod._MAGIC))
        f.write(off.to_bytes(8, "little"))
    with pytest.raises(pickle.UnpicklingError, match="forbidden"):
        ckpt_mod._load_obj(p)


# ------------------------------------------------------------ async saves

def test_async_save_roundtrip(tmp_path):
    eng = make_engine()
    for i in range(2):
        loss = eng.train_batch(lm_batch(i))
    path = eng.save_checkpoint(str(tmp_path), tag="a", async_save=True)
    assert path.endswith("a")
    ref = float(eng.train_batch(lm_batch(9)))
    eng.checkpoint_wait()                     # durable from here
    assert os.path.exists(os.path.join(str(tmp_path), "latest"))
    e2 = make_engine()
    e2.load_checkpoint(str(tmp_path), tag="a")
    got = float(e2.train_batch(lm_batch(9)))
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)


def test_async_save_config_key(tmp_path):
    eng = make_engine(**{"checkpoint": {"async_save": True}})
    eng.train_batch(lm_batch(0))
    eng.save_checkpoint(str(tmp_path), tag="cfg")
    eng.checkpoint_wait()
    # load_checkpoint also waits internally — a fresh engine must see it
    e2 = make_engine()
    p, _ = e2.load_checkpoint(str(tmp_path), tag="cfg")
    assert p is not None


def test_async_save_snapshot_isolated_from_next_step(tmp_path):
    # the snapshot must be host copies: stepping (and donating the device
    # buffers) right after save_checkpoint returns must not corrupt the
    # queued write
    eng = make_engine(1)
    eng.train_batch(lm_batch(0))
    eng.save_checkpoint(str(tmp_path), tag="s", async_save=True)
    ref = float(eng.train_batch(lm_batch(5)))   # donates old buffers
    eng.checkpoint_wait()
    e2 = make_engine(1)
    e2.load_checkpoint(str(tmp_path), tag="s")
    got = float(e2.train_batch(lm_batch(5)))
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)


# ------------------------------------------------- stage-3 native layout

def test_zero3_native_file_layout(tmp_path):
    eng = make_engine(3)
    eng.train_batch(lm_batch(0))
    eng.save_checkpoint(str(tmp_path), tag="z3")
    d = os.path.join(str(tmp_path), "z3")
    files = sorted(os.listdir(d))
    dp = eng.dp_world_size
    assert "mp_rank_00_model_states.pt" in files
    shard_files = [f for f in files if f.startswith("zero3_dp_rank_")]
    assert len(shard_files) == dp, files
    # the model file holds markers for partitioned leaves, not data
    raw = ckpt_mod._load_obj(os.path.join(d, "mp_rank_00_model_states.pt"))
    assert raw.get("zero3_native") is True
    qkv = raw["module"]["blocks"]["qkv_w"]
    assert ckpt_mod._z3_marker(qkv), qkv
    assert qkv[2] == dp
    # shard files carry param + master + both moments slices, keyed by
    # FLATTEN-ORDER leaf index (keystr is a debug label only — ADVICE r5:
    # formatted key strings broke on int-keyed dicts in the state tree)
    shard = ckpt_mod._load_obj(os.path.join(d, shard_files[0]))
    by_keystr = {r["keystr"]: (i, r) for i, r in shard["leaves"].items()}
    idx, rec = by_keystr["['blocks']['qkv_w']"]
    assert isinstance(idx, int)
    leaf_keys = [jax.tree_util.keystr(p) for p, _ in
                 jax.tree_util.tree_leaves_with_path(eng.params)]
    assert leaf_keys[idx] == "['blocks']['qkv_w']"
    assert rec["dim"] >= 0
    for field in ("param", "master", "m", "v"):
        assert rec[field] is not None
        assert np.asarray(rec[field]).shape[rec["dim"]] * dp == \
            eng.params["blocks"]["qkv_w"].shape[rec["dim"]]


def test_zero3_native_cross_stage_load(tmp_path):
    # a stage-3-native checkpoint must restore into a stage-0 engine
    # (markers rehydrate into full leaves) with optimizer state intact
    e3 = make_engine(3)
    for i in range(2):
        e3.train_batch(lm_batch(i))
    e3.save_checkpoint(str(tmp_path), tag="x")
    ref = float(e3.train_batch(lm_batch(7)))
    e0 = make_engine(0)
    e0.load_checkpoint(str(tmp_path), tag="x")
    got = float(e0.train_batch(lm_batch(7)))
    np.testing.assert_allclose(ref, got, rtol=5e-3, atol=5e-3)


def test_zero3_native_raw_weights_read(tmp_path):
    # load_module_tree (pretrain -> fine-tune path) must rehydrate markers
    e3 = make_engine(3)
    e3.train_batch(lm_batch(0))
    e3.save_checkpoint(str(tmp_path), tag="w")
    tree = ckpt_mod.load_module_tree(str(tmp_path), tag="w")
    got = np.asarray(tree["blocks"]["qkv_w"])
    want = np.asarray(e3.params["blocks"]["qkv_w"])
    np.testing.assert_array_equal(got, want)


def test_zero3_async_save_roundtrip(tmp_path):
    eng = make_engine(3)
    eng.train_batch(lm_batch(0))
    eng.save_checkpoint(str(tmp_path), tag="za", async_save=True)
    ref = float(eng.train_batch(lm_batch(4)))
    eng.checkpoint_wait()
    e2 = make_engine(3)
    e2.load_checkpoint(str(tmp_path), tag="za")
    got = float(e2.train_batch(lm_batch(4)))
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)
