"""MetricRegistry — the single exporter fan-out.

Before this layer the engine had three independent scalar-writing paths
(throughput logging, ``resilience/counters.py`` TensorBoard loops, the
compile-cache counters riding the same loop) and nothing machine-readable.
Now every producer registers a SOURCE — a callable returning
``{name: number}`` — and the registry emits one consistent snapshot per
report window to every attached SINK:

* :class:`TensorboardSink` — ``Train/<group>/<name>`` scalars through the
  engine's existing ``SummaryWriter`` (same tags the three legacy paths
  wrote, so dashboards keep working);
* :class:`JsonlSink` — one schema-versioned line per window
  (observability/schema.py), the artifact the CI smoke job validates and
  bench tooling diffs.

Sources are pulled at EMIT time (drain or boundary), never per step —
collection cost rides the report cadence, not the hot path.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

from deepspeed_tpu.observability import schema

logger = logging.getLogger(__name__)


class MetricRegistry:
    """Named metric sources fanned out to sinks (thread-safe: the spool
    drain callback runs on the runtime's callback thread)."""

    def __init__(self):
        self._sources: Dict[str, Callable[[], dict]] = {}
        self._sinks = []
        self._lock = threading.Lock()

    def register(self, group: str, source: Callable[[], dict]) -> None:
        """Register/replace the source for ``group`` (a callable returning
        a flat ``{name: number}`` dict, pulled at emit time)."""
        with self._lock:
            self._sources[group] = source

    def unregister(self, group: str) -> None:
        with self._lock:
            self._sources.pop(group, None)

    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def collect(self) -> Dict[str, dict]:
        """One snapshot of every source: ``{group: {name: value}}``.  A
        source that raises is skipped with a warning — observability must
        never take down training."""
        with self._lock:
            sources = dict(self._sources)
        out = {}
        for group, fn in sources.items():
            try:
                out[group] = dict(fn())
            except Exception as e:  # pragma: no cover - defensive
                logger.warning("telemetry source %r failed: %s", group, e)
        return out

    def counters_snapshot(self) -> dict:
        """Every source flattened to ``{"group/name": value}`` — the
        counter spelling both export cadences (window drain and legacy
        boundary) share."""
        out = {}
        for group, vals in self.collect().items():
            for name, val in vals.items():
                out[f"{group}/{name}"] = val
        return out

    def emit(self, event: dict, sample_count: Optional[int] = None) -> None:
        """Fan one window event (plus a fresh source snapshot) out to every
        sink.  ``event`` is the spool's window record; sinks receive it
        with ``counters`` filled from the collected snapshot."""
        event = dict(event)
        event.setdefault("counters", {}).update(self.counters_snapshot())
        self.emit_event(event, sample_count=sample_count)

    def emit_event(self, event: dict,
                   sample_count: Optional[int] = None) -> None:
        """Fan a pre-built event (fleet/startup — or a window event whose
        counters are already attached) out to every sink verbatim: no
        source collection, no counter merge — the fleet event's counters
        are a cross-host roll-up that a local snapshot must not clobber."""
        with self._lock:
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink.emit(event, sample_count=sample_count)
            except Exception as e:  # pragma: no cover - defensive
                logger.warning("telemetry sink %r failed: %s",
                               type(sink).__name__, e)

    def close(self) -> None:
        with self._lock:
            sinks, self._sinks = list(self._sinks), []
        for sink in sinks:
            try:
                sink.close()
            except Exception:  # pragma: no cover - defensive
                pass


class TensorboardSink:
    """Window events as ``Train/*`` scalars through an existing
    SummaryWriter — the dedup target of the three legacy write loops.
    Scalar tags: window metrics under ``Train/Telemetry/*``, counter
    groups under ``Train/<Group>/<name>`` (``Train/Resilience/*`` keeps
    its PR 4/5 spelling, so existing dashboards keep working)."""

    #: window-event fields exported as Train/Telemetry/* scalars
    _WINDOW_FIELDS = ("loss", "loss_mean", "grad_norm", "loss_scale",
                      "skipped", "step_ms", "samples_per_sec", "mfu",
                      "host_ms", "data_wait_ms",
                      "measured_peak_hbm_gb", "hbm_drift",
                      "predicted_peak_hbm_gb", "predicted_boundary_ms",
                      "measured_boundary_ms", "boundary_drift")

    #: fleet-event fields exported as Train/Fleet/* scalars (rank 0)
    _FLEET_FIELDS = ("reported_hosts", "step_ms_min", "step_ms_median",
                     "step_ms_max", "host_ms_min", "host_ms_median",
                     "host_ms_max", "samples_per_sec_sum",
                     "straggler_index", "loss_mean", "loss_spread",
                     "skipped_total")

    #: startup-event fields exported once as Train/Telemetry/* scalars
    _STARTUP_FIELDS = ("time_to_first_step_s", "first_dispatch_s",
                       "restore_seconds")

    def __init__(self, writer):
        #: a SummaryWriter, or a zero-arg callable resolving one LIVE —
        #: the engine's writer may be replaced after construction (tests
        #: inject fakes; users wire writers late), so the sink must not
        #: capture a stale reference
        self._writer = writer

    @property
    def writer(self):
        w = self._writer
        return w() if callable(w) else w

    def emit(self, event: dict, sample_count: Optional[int] = None) -> None:
        writer = self.writer
        if writer is None:
            return
        x = sample_count if sample_count is not None else event["step"]
        sid = event.get("schema")
        if sid == schema.FLEET_SCHEMA_ID:
            # rank-0 fleet roll-up: spread/straggler scalars + the count
            # of flagged ranks (the alarmable number); per_host detail
            # stays in the JSONL record
            for name in self._FLEET_FIELDS:
                val = event.get(name)
                if val is not None:
                    writer.add_scalar(f"Train/Fleet/{name}", float(val), x)
            writer.add_scalar("Train/Fleet/stragglers",
                              float(len(event.get("stragglers") or [])), x)
            writer.add_scalar("Train/Fleet/missing_hosts",
                              float(len(event.get("missing_hosts") or [])),
                              x)
            return
        if sid == schema.STARTUP_SCHEMA_ID:
            for name in self._STARTUP_FIELDS:
                val = event.get(name)
                if val is not None:
                    writer.add_scalar(f"Train/Telemetry/{name}",
                                      float(val), x)
            return
        for name in self._WINDOW_FIELDS:
            val = event.get(name)
            if val is not None:
                writer.add_scalar(f"Train/Telemetry/{name}",
                                  float(val), x)
        for key, val in event.get("counters", {}).items():
            group, _, name = key.partition("/")
            writer.add_scalar(
                f"Train/{group.capitalize()}/{name}", float(val), x)

    def close(self) -> None:
        pass        # the writer belongs to the engine


class JsonlSink:
    """One schema-stamped JSON line per event, flushed per emit (the file
    must be complete up to the last drained window when the process is
    preempted — the flush-on-drain contract the resilience driver relies
    on).  Events carrying their own ``schema`` stamp (fleet/startup) pass
    through; unstamped events are window events and get the window schema
    + null-filled field set.  Lines that fail self-validation are still
    written but logged loudly: a schema bug must be visible in CI, not
    silently dropped."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        # window emits arrive on the runtime callback thread, fleet emits
        # on the aggregator thread — interleaved partial writes would
        # corrupt the line framing the validator gates on
        self._lock = threading.Lock()

    def emit(self, event: dict, sample_count: Optional[int] = None) -> None:
        event = dict(event)
        if event.get("schema") is None:
            event["schema"] = schema.SCHEMA_ID
            event["version"] = schema.SCHEMA_VERSION
            # every schema field present (null when unmeasured): a missing
            # column and an unmeasured column are different facts
            for name in schema.FIELDS:
                event.setdefault(name, None)
        event.setdefault("ts", time.time())
        msg = schema.validate_any(event)
        if msg is not None:  # pragma: no cover - schema bug guard
            logger.error("telemetry event fails its own schema (%s): %r",
                         msg, event)
        line = json.dumps(event) + "\n"
        with self._lock:
            self._f.write(line)
            self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:  # pragma: no cover - defensive
            pass
