"""Capacity planner, memory half: static per-device peak-HBM prediction.

ZeRO's whole pitch is memory *arithmetic* — optimizer states 12/dp bytes
per parameter, grads 4/dp under stage 2, params 2/dp under stage 3 — yet
until this pass the repo only learned whether a config fits by compiling
and OOMing.  This module turns the question into a static query: walk the
traced step program (the same jaxprs graph-lint already covers, with the
per-device *local* shapes the ``shard_map`` body carries) and simulate
XLA's buffer assignment well enough to predict the per-device peak.

The walk (:func:`peak_of`) is a liveness simulation over one jaxpr level:

* every equation's outputs allocate; buffers free after their last use;
* ``reshape``/``transpose``-style ops alias (XLA bitcasts them);
* elementwise ops reuse a dying same-size input buffer (XLA fuses the
  chain and writes in place);
* ``scan`` carries update in place (XLA aliases while-loop state) and the
  stacked ``ys`` — the *scan residuals*, including everything remat
  decides to save — allocate up front for the whole trip count, so remat
  on/off changes the prediction exactly the way it changes the program;
* call-like primitives (``pjit``/``remat2``/``cond``/custom-vjp) peak at
  ``max(outer live + inner peak, outer live + own outputs)`` — inner
  scratch and the call's results never coexist;
* jaxpr outputs matching a *donated* input's shape/dtype are free (XLA
  input/output aliasing — the engine donates master/opt-state/loss-scale
  into every step);
* on CPU only (``profile.lowp_dot_f32_copies``): each fp16/bf16 dot
  operand/result charges a transient fp32 copy — the host has no native
  half GEMM.  TPU predictions must not carry this.

Accuracy contract: tests/test_memplan.py pins the prediction against
``compiled.memory_analysis()`` across ZeRO stages 0-3 x remat on/off x
MP/PP at +-10% (with a small absolute floor for toy-scale
buffer-assignment noise).  The ZeRO-3 paired-gather prefetch transient —
documented in docs/scaling.md as "budget two gathered layers" — stops
being prose here: :func:`zero3_prefetch_transient_bytes` computes it from
the engine's own dims tree, and the walk reproduces it from the traced
program.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.analysis import graph as G
from deepspeed_tpu.analysis import profiles as prof_mod
from deepspeed_tpu.analysis import report as R

# --------------------------------------------------------------- primitives

#: pure layout changes XLA lowers to bitcasts / fuses into the consumer
ALIAS_PRIMS = frozenset({
    "reshape", "squeeze", "expand_dims", "bitcast_convert_type", "copy",
    "stop_gradient", "transpose", "rev",
})

#: elementwise ops XLA fuses and computes in place over a dying operand
ELEMENTWISE_PRIMS = frozenset({
    "add", "sub", "mul", "div", "max", "min", "rem", "pow", "atan2", "and",
    "or", "xor", "not", "neg", "sign", "floor", "ceil", "round", "exp",
    "log", "log1p", "expm1", "tanh", "logistic", "erf", "erf_inv", "erfc",
    "sqrt", "rsqrt", "cbrt", "integer_pow", "abs", "cos", "sin", "tan",
    "convert_element_type", "select_n", "clamp", "nextafter", "is_finite",
    "eq", "ne", "ge", "gt", "le", "lt", "add_any", "square",
})

#: sub-jaxpr carriers whose scratch and outputs never coexist
CALL_PRIMS = frozenset({
    "pjit", "remat2", "remat", "custom_vjp_call_jaxpr", "custom_jvp_call",
    "custom_vjp_call", "closed_call", "core_call", "xla_call", "cond",
    "switch", "while",
})

DOT_PRIMS = frozenset({"dot_general", "conv_general_dilated"})

#: contributors kept per peak snapshot (the error message's top-N)
_TOP_K = 12


def nbytes(aval) -> int:
    """Buffer bytes of one abstract value (bools are byte-wide in XLA)."""
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except Exception:       # symbolic dims: refuse to guess small
            return 1 << 62
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return 4 * n
    return n * max(1, np.dtype(dt).itemsize)


def _is_lowp(aval) -> bool:
    dt = getattr(aval, "dtype", None)
    return dt is not None and str(dt) in ("float16", "bfloat16")


@dataclasses.dataclass
class Contributor:
    """One buffer alive at the predicted peak."""

    bytes: int
    label: str                  # producing primitive, or the argument leaf path
    shape: Tuple[int, ...]
    dtype: str
    path: str = ""              # jaxpr path ("scan/remat2")
    source: str = ""            # "file:line (function)" when jax recorded one

    def format(self) -> str:
        loc = self.source or self.path or ""
        where = f"  @ {loc}" if loc else ""
        return (f"{self.bytes / 2**20:8.2f} MiB  {self.label:24s} "
                f"{self.dtype}{list(self.shape)}{where}")


@dataclasses.dataclass
class ProgramPlan:
    """Predicted per-device memory envelope of one step program."""

    subject: str
    argument_bytes: int         # persistent inputs (params/master/opt/batch)
    peak_bytes: int             # predicted per-device peak HBM
    contributors: List[Contributor]

    @property
    def transient_bytes(self) -> int:
        return max(0, self.peak_bytes - self.argument_bytes)

    def top_contributors(self, k: int = 5) -> List[Contributor]:
        return sorted(self.contributors, key=lambda c: -c.bytes)[:k]


def _peak_of(jaxpr, donated=None, lowp_dot_copies: bool = False,
             path: str = "") -> Tuple[int, List[Contributor]]:
    """Liveness walk over one (open or closed) jaxpr level.

    Returns ``(peak_extra_bytes, contributors)``: the peak of allocations
    this level makes beyond its own invars (the caller owns those), and
    the owned buffers alive at that peak (flattened through the inner
    level the peak passed through)."""
    j = G._as_open_jaxpr(jaxpr)
    if j is None:
        return 0, []

    last = {}
    for i, eqn in enumerate(j.eqns):
        for v in eqn.invars:
            if G.is_var(v):
                last[v] = i
    n_eqns = len(j.eqns)
    for v in j.outvars:
        if G.is_var(v):
            last[v] = n_eqns

    # donation pool: outvars may land in a dying donated-argument buffer,
    # matched by (shape, dtype) multiset exactly like XLA's aliasing
    donate_pool: dict = {}
    for v in donated or ():
        key = (tuple(getattr(v.aval, "shape", ())),
               str(getattr(v.aval, "dtype", "")))
        donate_pool[key] = donate_pool.get(key, 0) + 1

    alive: dict = {}            # var -> owned bytes (0 = alias/reused view)
    meta: dict = {}             # var -> (label, source)
    cur = 0
    peak = 0
    peak_snapshot: List[Contributor] = []

    def snapshot(inner_contribs: List[Contributor]) -> List[Contributor]:
        own = [Contributor(bytes=b, label=meta.get(v, ("?", ""))[0],
                           shape=tuple(getattr(v.aval, "shape", ())),
                           dtype=str(getattr(v.aval, "dtype", "")),
                           path=path, source=meta.get(v, ("?", ""))[1])
               for v, b in alive.items() if b > 0]
        own.sort(key=lambda c: -c.bytes)
        return (own + inner_contribs)[:_TOP_K]

    for i, eqn in enumerate(j.eqns):
        name = eqn.primitive.name
        subs = G.subjaxprs(eqn)
        inner = 0
        inner_contribs: List[Contributor] = []
        for label, sub in subs:
            sub_path = f"{path}/{label}" if path else label
            p, c = _peak_of(sub, lowp_dot_copies=lowp_dot_copies,
                            path=sub_path)
            if p > inner:
                inner, inner_contribs = p, c

        dying = [iv for iv in eqn.invars if G.is_var(iv)
                 and last.get(iv) == i and alive.get(iv, 0) > 0]
        out_assign: dict = {}
        new_alloc = 0

        def place(v, allow_reuse: bool) -> None:
            """Assign an output buffer: donated-alias > in-place reuse >
            fresh allocation."""
            nonlocal new_alloc
            need = nbytes(v.aval)
            if G.is_var(v) and last.get(v) == n_eqns:
                key = (tuple(getattr(v.aval, "shape", ())),
                       str(getattr(v.aval, "dtype", "")))
                if donate_pool.get(key, 0) > 0:
                    donate_pool[key] -= 1
                    out_assign[v] = 0
                    return
            if allow_reuse:
                for iv in dying:
                    if alive.get(iv, 0) >= need:
                        dying.remove(iv)
                        out_assign[v] = alive[iv]
                        alive[iv] = 0       # ownership transferred
                        return
            out_assign[v] = need
            new_alloc += need

        # CPU fp32-GEMM quirk: half-precision dot operands/results charge
        # a transient fp32 copy at the dot (2x their half-width bytes)
        extra_during = 0
        if lowp_dot_copies and name in DOT_PRIMS:
            seen = set()
            for iv in eqn.invars:
                if _is_lowp(getattr(iv, "aval", None)) and id(iv) not in seen:
                    seen.add(id(iv))
                    extra_during += 2 * nbytes(iv.aval)
            for ov in eqn.outvars:
                if _is_lowp(ov.aval):
                    extra_during += 2 * nbytes(ov.aval)

        if name in ALIAS_PRIMS:
            # the view shares the source's storage: if the source var
            # dies HERE, ownership moves to the view (its bytes stay
            # live until the view's own last use), otherwise the view
            # owns nothing — freeing the source while the reshape lives
            # would underpredict the peak
            alias_src = next(
                (iv for iv in eqn.invars if G.is_var(iv)), None)
            for v in eqn.outvars:
                if (alias_src is not None
                        and last.get(alias_src) == i
                        and alive.get(alias_src, 0) > 0):
                    out_assign[v] = alive[alias_src]
                    alive[alias_src] = 0    # ownership transferred
                    alias_src = None
                else:
                    out_assign[v] = 0
            during = cur
        elif name == "scan":
            num_carry = int(eqn.params.get("num_carry", 0))
            for k, v in enumerate(eqn.outvars):
                place(v, allow_reuse=(k < num_carry))
            during = cur + new_alloc + inner
        elif name in CALL_PRIMS:
            for v in eqn.outvars:
                place(v, allow_reuse=False)
            during = max(cur + inner, cur + new_alloc)
        elif name in ELEMENTWISE_PRIMS:
            for v in eqn.outvars:
                place(v, allow_reuse=True)
            during = cur + new_alloc
        else:
            for v in eqn.outvars:
                place(v, allow_reuse=False)
            during = cur + new_alloc + inner + extra_during

        cur += new_alloc
        src = G.source_of(eqn)
        for v in out_assign:
            meta[v] = (name, src)
        high = max(during, cur)
        if high > peak:
            peak = high
            alive.update(out_assign)
            peak_snapshot = snapshot(inner_contribs if during >= cur else [])
        else:
            alive.update(out_assign)
        for v in list(alive):
            if last.get(v, -1) <= i:
                cur -= alive.pop(v)

    return peak, peak_snapshot


def _find_shard_map_body(closed_jaxpr):
    """The shard_map body jaxpr of an engine program — the level whose
    shapes are already per-device.  None for plain (unsharded) programs."""
    for eqn, _ in G.walk(closed_jaxpr):
        if eqn.primitive.name == "shard_map":
            subs = G.subjaxprs(eqn)
            if subs:
                return subs[0][1]
    return None


def analyze_program(fn, args, donate_argnums: Sequence[int] = (),
                    arg_labels=None, subject: str = "",
                    profile: Optional[prof_mod.BackendProfile] = None,
                    closed=None) -> ProgramPlan:
    """Predict the per-device peak HBM of ``fn(*args)``.

    ``args`` are example values/ShapeDtypeStructs (never executed — the
    program is traced abstractly).  ``donate_argnums`` must match the
    jit-level donation so output aliasing is modeled.  ``arg_labels``
    (optional, same length as ``args``) names argument groups so peak
    contributors carry engine leaf paths instead of "arg 3".  ``closed``
    accepts a pre-traced ``jax.make_jaxpr(fn)(*args)`` so one trace can
    feed both planner halves."""
    if profile is None:
        profile = prof_mod.default_profile()
    quirk = bool(profile is not None and profile.lowp_dot_f32_copies)

    if closed is None:
        closed = jax.make_jaxpr(fn)(*args)
    body = _find_shard_map_body(closed) or G._as_open_jaxpr(closed)

    # map flat argument positions to body invars (tree-flatten order is
    # the shard_map calling convention)
    leaf_counts = [len(jax.tree_util.tree_leaves(a)) for a in args]
    labels: List[str] = []
    for pos, a in enumerate(args):
        head = (arg_labels[pos] if arg_labels and pos < len(arg_labels)
                else f"arg{pos}")
        flat = jax.tree_util.tree_flatten_with_path(a)[0]
        if len(flat) == 1:
            labels.append(str(head))
        else:
            labels.extend(f"{head}{jax.tree_util.keystr(p)}"
                          for p, _ in flat)
    invars = list(body.invars)
    donated = []
    off = 0
    for pos, n in enumerate(leaf_counts):
        if pos in set(donate_argnums):
            donated.extend(invars[off:off + n])
        off += n

    arg_bytes = sum(nbytes(v.aval) for v in invars)
    extra, contribs = _peak_of(body, donated=donated,
                               lowp_dot_copies=quirk)

    # argument leaves are live for the whole program: they are peak
    # contributors too, named by their engine leaf path
    arg_contribs = [
        Contributor(bytes=nbytes(v.aval),
                    label=(labels[k] if k < len(labels) else f"arg{k}"),
                    shape=tuple(getattr(v.aval, "shape", ())),
                    dtype=str(getattr(v.aval, "dtype", "")),
                    path="<argument>")
        for k, v in enumerate(invars)]
    merged = sorted(arg_contribs + contribs, key=lambda c: -c.bytes)[:_TOP_K]
    return ProgramPlan(subject=subject, argument_bytes=arg_bytes,
                       peak_bytes=arg_bytes + extra, contributors=merged)


# ----------------------------------------------------------- engine surface

@dataclasses.dataclass
class CapacityPlan:
    """Fit verdict of one engine + batch format against a profile."""

    programs: List[ProgramPlan]
    persistent: dict                        # engine.memory_estimate()
    profile: Optional[prof_mod.BackendProfile]
    budget_bytes: Optional[int]
    zero3_prefetch_bytes: int = 0           # computed two-layer envelope
    comm: Optional[object] = None           # whole-step commplan.CommPlan
    boundary_comm: Optional[object] = None  # step-program-only CommPlan

    @property
    def peak_bytes(self) -> int:
        return max((p.peak_bytes for p in self.programs), default=0)

    @property
    def peak_program(self) -> Optional[ProgramPlan]:
        return max(self.programs, key=lambda p: p.peak_bytes, default=None)

    def fits(self) -> Optional[bool]:
        if self.budget_bytes is None:
            return None
        return self.peak_bytes <= self.budget_bytes

    def headroom_bytes(self) -> Optional[int]:
        if self.budget_bytes is None:
            return None
        return self.budget_bytes - self.peak_bytes

    # -------------------------------------------------------------- report

    def to_report(self, subject: str = "capacity plan") -> R.Report:
        """Findings under the ``memory.*`` rule family (same severity /
        suppression machinery as graph lint — docs/analysis.md)."""
        rep = R.Report(subject=subject)
        peak = self.peak_bytes
        prog = self.peak_program
        where = prog.subject if prog else "<no program>"
        if self.comm is not None:
            # the comm.* family's one (info) rule so far: the wire
            # roll-up, suppressible like any other code
            rep.add("comm.wire", R.INFO, self.comm.format_summary(),
                    path=self.comm.subject, pass_name="commplan")
        if self.budget_bytes is None:
            rep.add(
                "memory.no-budget", R.INFO,
                f"predicted per-device peak HBM is "
                f"{_fmt_bytes(peak)} ({where}); no memory budget "
                f"configured — set analysis.memory_budget_gb or a "
                f"--profile to gate it",
                pass_name="memplan")
            return rep
        budget = self.budget_bytes
        if peak > budget:
            tops = "\n".join(
                "            " + c.format()
                for c in (prog.top_contributors(5) if prog else []))
            rep.add(
                "memory.budget-exceeded", R.ERROR,
                f"predicted per-device peak HBM {_fmt_bytes(peak)} "
                f"exceeds the budget {_fmt_bytes(budget)}"
                + (f" (profile {self.profile.name})" if self.profile
                   else "")
                + f" in program '{where}'.  Top live-set contributors:\n"
                + tops,
                path=where, pass_name="memplan")
        elif peak > 0.9 * budget:
            rep.add(
                "memory.budget", R.WARNING,
                f"predicted per-device peak HBM {_fmt_bytes(peak)} is "
                f"within 10% of the {_fmt_bytes(budget)} budget "
                f"({where}); one batch-size or remat change from OOM",
                path=where, pass_name="memplan")
        else:
            rep.add(
                "memory.fit", R.INFO,
                f"predicted per-device peak HBM {_fmt_bytes(peak)} "
                f"fits the {_fmt_bytes(budget)} budget "
                f"(headroom {_fmt_bytes(self.headroom_bytes())})",
                path=where, pass_name="memplan")
        return rep

    # ---------------------------------------------------------- fit table

    def format_table(self) -> str:
        lines = []
        name = self.profile.name if self.profile else "<none>"
        budget = (f"{self.budget_bytes / 2**30:.3f} GiB"
                  if self.budget_bytes is not None else "unset")
        lines.append(f"profile {name}  budget {budget}")
        lines.append(f"{'program':<14} {'args':>12} {'transient':>12} "
                     f"{'peak':>12}  fit")
        for p in self.programs:
            fit = "-"
            if self.budget_bytes is not None:
                fit = "OK" if p.peak_bytes <= self.budget_bytes else "OVER"
            lines.append(
                f"{p.subject:<14} {p.argument_bytes / 2**20:>10.2f}Mi "
                f"{p.transient_bytes / 2**20:>10.2f}Mi "
                f"{p.peak_bytes / 2**20:>10.2f}Mi  {fit}")
        pers = self.persistent
        if pers:
            lines.append(
                "persistent: params "
                f"{pers['params_bytes'] / 2**20:.2f}Mi + optimizer "
                f"{pers['optimizer_state_bytes'] / 2**20:.2f}Mi + grad-acc "
                f"{pers['grad_accumulator_bytes'] / 2**20:.2f}Mi "
                f"(zero_stage={pers['zero_stage']})")
            if "kv_cache_bytes" in pers:
                # serving plans (inference/engine.py) carry the
                # preallocated KV page pool as a persistent line item
                lines.append(
                    f"kv cache: {pers['kv_cache_bytes'] / 2**20:.2f}Mi "
                    f"preallocated (page pool)")
            if "draft_params_bytes" in pers:
                # speculative decoding: the draft model's weights and
                # its (plain, unshared) KV pool ride the budget too
                lines.append(
                    f"draft: params "
                    f"{pers['draft_params_bytes'] / 2**20:.2f}Mi + "
                    f"kv cache "
                    f"{pers.get('draft_kv_cache_bytes', 0) / 2**20:.2f}Mi")
        if self.zero3_prefetch_bytes:
            lines.append(
                f"zero3 prefetch transient: "
                f"{self.zero3_prefetch_bytes / 2**20:.2f}Mi "
                f"(two gathered layers)")
        if self.comm is not None:
            lines.append(self.comm.format_summary())
        return "\n".join(lines)

    def to_json(self) -> dict:
        out = {
            "profile": self.profile.name if self.profile else None,
            "budget_bytes": self.budget_bytes,
            "peak_bytes": self.peak_bytes,
            "fits": self.fits(),
            "persistent": dict(self.persistent),
            "zero3_prefetch_bytes": self.zero3_prefetch_bytes,
            "programs": [{
                "subject": p.subject,
                "argument_bytes": p.argument_bytes,
                "transient_bytes": p.transient_bytes,
                "peak_bytes": p.peak_bytes,
                "top_contributors": [{
                    "bytes": c.bytes, "label": c.label,
                    "shape": list(c.shape), "dtype": c.dtype,
                    "path": c.path, "source": c.source,
                } for c in p.top_contributors(5)],
            } for p in self.programs],
        }
        if self.comm is not None:
            out["comm"] = self.comm.to_json()
        if self.boundary_comm is not None:
            out["boundary_comm"] = self.boundary_comm.to_json()
        return out


def _fmt_bytes(n: int) -> str:
    """GiB at real scale, MiB below 0.01 GiB — '0.000 GiB exceeds the
    budget 0.000 GiB' helps nobody at toy scale."""
    if abs(n) >= int(0.01 * 2**30):
        return f"{n / 2**30:.3f} GiB"
    return f"{n / 2**20:.3f} MiB"


def zero3_prefetch_transient_bytes(engine) -> int:
    """The ZeRO-3 paired-gather transient, COMPUTED: two gathered layers'
    compute-dtype bytes (docs/scaling.md's documented envelope).  Block
    leaves are the ones partitioned at dim >= 1 — ``zero3_min_dims`` pins
    the leading scan/layer axis as never-partitioned, so a partition dim
    of 1+ identifies a per-layer [L, ...] stack; gathering restores the
    full per-layer slice (size / L).  0 when prefetch is off, the engine
    is not stage 3, or the stack depth makes ``scan_layers`` fall back
    to on-demand gathers (L < 2 or odd — transformer.py's exact
    condition; the paired-gather transient only exists when the paired
    scan actually runs)."""
    import jax.numpy as jnp

    dims = getattr(engine, "_zero3_dims", None)
    if dims is None or not getattr(engine, "overlap_comm", False):
        return 0
    itemsize = jnp.dtype(engine.policy.compute_dtype).itemsize
    leaves = jax.tree_util.tree_leaves(engine.params)
    dim_leaves = jax.tree_util.tree_structure(
        engine.params).flatten_up_to(dims)
    layer = 0
    depth = None
    for leaf, d in zip(leaves, dim_leaves):
        if int(d) >= 1 and leaf.ndim >= 1 and leaf.shape[0] > 0:
            if depth is None:
                depth = int(leaf.shape[0])
            layer += (int(leaf.size) // int(leaf.shape[0])) * itemsize
    if depth is None or depth < 2 or depth % 2:
        return 0
    return 2 * layer


def _engine_train_batch_args(engine, batch):
    # the protocol owner lives in the package __init__ (PR 3: callers
    # must not hand-marshal the tuple); lazy import avoids the cycle
    from deepspeed_tpu import analysis
    return analysis.train_batch_args(engine, batch)


def _engine_step_args(engine, grads):
    from deepspeed_tpu import analysis
    return analysis.step_args(engine, grads)


#: argument labels of the fused call protocol (analysis.train_batch_args).
#: The optional metric-spool state is appended LAST — argument offsets 0..7
#: stay aligned with the shard_map body invars whether or not it is there
#: (the spool append runs OUTSIDE the shard_map, at the jit level).
_TRAIN_BATCH_LABELS = ("params", "master", "opt_state", "loss_scale",
                       "hypers", "zero_norm_w", "zero_gid", "batch",
                       "spool")

#: K-fused call protocol (analysis.train_many_args): the hyper slot is
#: the [K, 4, G] block, "live" the cond predicate input, "batch" the
#: tuple of K per-step batch trees
_TRAIN_MANY_LABELS = ("params", "master", "opt_state", "loss_scale",
                      "hypers", "zero_norm_w", "zero_gid", "live",
                      "batch", "spool")


def plan_engine(engine, batch, train: bool = True,
                profile: Optional[prof_mod.BackendProfile] = None,
                budget_bytes: Optional[int] = None, fused: bool = True,
                with_comm: bool = True,
                steps_per_dispatch: Optional[int] = None) -> CapacityPlan:
    """Full capacity plan for one engine + batch format.

    ``fused=True`` plans the fused ``train_batch`` program (the
    production step — fwd, bwd, boundary collectives AND the optimizer in
    one trace); ``fused=False`` plans the split-API pair (``fwdbwd`` per
    micro-batch + the ``step`` boundary program), whose step-only
    :class:`~.commplan.CommPlan` is the predicted *boundary* wire time.
    ``steps_per_dispatch`` (default: the engine's configured K) > 1
    plans the ACTUAL K-fused ``train_many`` program — which holds K full
    effective batches as simultaneous inputs, so pricing the single-step
    program would under-count ~(K-1) batch copies of residency and let
    an over-HBM K config through the error gate.  (Its CommPlan prices
    one DISPATCH = K optimizer steps.)
    ``budget_bytes=None`` = report-only (``memory.no-budget``); callers
    gating against a profile pass ``profile.hbm_bytes`` themselves (the
    engine/CLI do, for *explicitly chosen* profiles — the
    memory-model-quirk default below must never become a surprise
    budget).  Each program is traced abstractly exactly ONCE; both
    planner halves share the jaxpr."""
    from deepspeed_tpu.analysis import commplan

    batch = tuple(batch) if isinstance(batch, (tuple, list)) else (batch,)
    if profile is None:
        profile = prof_mod.default_profile()
    if steps_per_dispatch is None:
        steps_per_dispatch = int(getattr(engine, "steps_per_dispatch", 1))
    k = steps_per_dispatch if (train and fused) else 1
    mesh_shape = dict(engine.mesh.shape)
    multi_host = jax.process_count() > 1

    programs = []
    comm = None
    boundary_comm = None
    if train and fused and k > 1:
        from deepspeed_tpu import analysis as _analysis
        key = (k, engine._batch_cache_key(batch))
        fn = engine._cached_batch_fn(
            engine._train_many_fns, key,
            lambda: engine._build_train_many(batch, k))
        args = _analysis.train_many_args(
            engine, tuple(batch for _ in range(k)))
        donate = engine._donate_argnums(fused=True)
        closed = jax.make_jaxpr(fn)(*args)
        programs.append(analyze_program(
            fn, args, donate_argnums=donate,
            arg_labels=_TRAIN_MANY_LABELS, subject="train_many",
            profile=profile, closed=closed))
        if with_comm:
            comm = commplan.analyze_comm(
                closed, mesh_shape, profile=profile,
                subject="train_many", multi_host=multi_host)
    elif train and fused:
        key = engine._batch_cache_key(batch)
        fn = engine._cached_batch_fn(
            engine._train_batch_fns, key,
            lambda: engine._build_train_batch(batch))
        args = _engine_train_batch_args(engine, batch)
        donate = engine._donate_argnums(fused=True)
        closed = jax.make_jaxpr(fn)(*args)
        programs.append(analyze_program(
            fn, args, donate_argnums=donate,
            arg_labels=_TRAIN_BATCH_LABELS, subject="train_batch",
            profile=profile, closed=closed))
        if with_comm:
            comm = commplan.analyze_comm(
                closed, mesh_shape, profile=profile,
                subject="train_batch", multi_host=multi_host)
    elif train:
        # split API: fwdbwd over one micro-batch + the boundary step
        fwdbwd = engine._ensure_fwdbwd(batch)
        fb_args = (engine.params, engine.loss_scale_state.cur_scale, batch)
        fb_closed = jax.make_jaxpr(fwdbwd)(*fb_args)
        programs.append(analyze_program(
            fwdbwd, fb_args, arg_labels=("params", "loss_scale", "batch"),
            subject="fwdbwd", profile=profile, closed=fb_closed))
        _, grad_shapes = jax.eval_shape(fwdbwd, *fb_args)
        if engine._step_fn is None:
            engine._step_fn = engine._build_step()
        st_args = _engine_step_args(engine, grad_shapes)
        donate = engine._donate_argnums(fused=False)
        st_closed = jax.make_jaxpr(engine._step_fn)(*st_args)
        programs.append(analyze_program(
            engine._step_fn, st_args, donate_argnums=donate,
            arg_labels=("master", "opt_state", "grads", "loss_scale",
                        "hypers", "zero_norm_w", "zero_gid"),
            subject="step", profile=profile, closed=st_closed))
        if with_comm:
            fb_comm = commplan.analyze_comm(
                fb_closed, mesh_shape, profile=profile, subject="fwdbwd",
                multi_host=multi_host)
            boundary_comm = commplan.analyze_comm(
                st_closed, mesh_shape, profile=profile, subject="step",
                multi_host=multi_host)
            gas = engine.gradient_accumulation_steps()
            comm = commplan.CommPlan(
                subject="fwdbwd*gas+step",
                costs=[dataclasses.replace(
                    c, executions=c.executions * gas)
                    for c in fb_comm.costs] + list(boundary_comm.costs),
                mesh_shape=mesh_shape, profile=profile,
                multi_host=multi_host)
    else:
        ev = engine._ensure_eval(batch)
        ev_closed = jax.make_jaxpr(ev)(engine.params, batch)
        programs.append(analyze_program(
            ev, (engine.params, batch), arg_labels=("params", "batch"),
            subject="eval", profile=profile, closed=ev_closed))
        if with_comm:
            comm = commplan.analyze_comm(
                ev_closed, mesh_shape, profile=profile, subject="eval",
                multi_host=multi_host)

    return CapacityPlan(
        programs=programs,
        persistent=engine.memory_estimate(),
        profile=profile,
        budget_bytes=budget_bytes,
        zero3_prefetch_bytes=zero3_prefetch_transient_bytes(engine),
        comm=comm, boundary_comm=boundary_comm)
