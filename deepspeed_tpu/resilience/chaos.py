"""Deterministic fault injection for the chaos test suite.

Every injection point is keyed by explicit configuration — an env var (so a
launcher-spawned subprocess can be armed from outside) or the programmatic
``configure()`` twin — and is a no-op when unarmed, so production code paths
carry only a cheap attribute check.  Points are *deterministic*: "fail the
first N writes", "SIGTERM at step K on rank R", never random, so a chaos
test failure reproduces exactly.

Injection points (wired by checkpoint.py and resilience.driver):

==============================  ==============================================
``io_point("ckpt_write")``      raises ``IOError`` for the first
                                ``io_fail_writes`` checkpoint file writes
                                (``DSTPU_CHAOS_IO_FAIL_WRITES``)
``read_point("ckpt_read")``     raises ``IOError`` for the first
                                ``io_fail_reads`` restore chunk reads
                                (``DSTPU_CHAOS_IO_FAIL_READS``) — hit by
                                every restore reader, serial or pooled,
                                so the per-reader ``io_retry`` budget is
                                exercisable deterministically
``step_point(step, rank)``      at ``sigterm_step`` on ``sigterm_rank``
                                sends SIGTERM to this process
                                (``DSTPU_CHAOS_SIGTERM_STEP`` /
                                ``DSTPU_CHAOS_RANK``)
``maybe_stall(step)``           inside the engine's watchdog-armed
                                boundary region: stalls ``stall_s``
                                seconds in the recognisably-named
                                ``chaos_stall`` frame at ``stall_step``
                                (``DSTPU_CHAOS_STALL_STEP`` /
                                ``DSTPU_CHAOS_STALL_S``)
``nan_at(step)``                True at ``nan_step``
                                (``DSTPU_CHAOS_NAN_STEP``); the driver then
                                poisons the batch with ``poison_batch`` so
                                the step's loss/grads go non-finite and the
                                engine's NaN/Inf sentinel must absorb it
==============================  ==============================================

The catalog lives in docs/resilience.md ("Fault-injection points").
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time

logger = logging.getLogger(__name__)

ENV_IO_FAIL_WRITES = "DSTPU_CHAOS_IO_FAIL_WRITES"
ENV_IO_FAIL_READS = "DSTPU_CHAOS_IO_FAIL_READS"
ENV_SIGTERM_STEP = "DSTPU_CHAOS_SIGTERM_STEP"
ENV_CHAOS_RANK = "DSTPU_CHAOS_RANK"
ENV_STALL_STEP = "DSTPU_CHAOS_STALL_STEP"
ENV_STALL_S = "DSTPU_CHAOS_STALL_S"
ENV_NAN_STEP = "DSTPU_CHAOS_NAN_STEP"


class _State:
    def __init__(self):
        self.io_fail_writes = 0     # fail this many io_point() calls, then heal
        self.io_fail_reads = 0      # fail this many read_point() calls
        self.sigterm_step = None    # SIGTERM self at this step
        self.sigterm_rank = None    # ...only on this rank (None = every rank)
        self.stall_step = None      # stall at this step
        self.stall_s = 0.0          # ...for this long
        self.stall_until = None     # ...or until this Event fires
                                    # (programmatic-only: tests end the
                                    # stall when the watchdog reacted)
        self.nan_step = None        # poison the batch at this step


_state = _State()


def _env_int(name):
    v = os.environ.get(name, "").strip()
    return int(v) if v else None


def reload_env() -> None:
    """(Re-)read the DSTPU_CHAOS_* env vars into the injection state —
    called once at import; call again after mutating os.environ in-process."""
    _state.io_fail_writes = _env_int(ENV_IO_FAIL_WRITES) or 0
    _state.io_fail_reads = _env_int(ENV_IO_FAIL_READS) or 0
    _state.sigterm_step = _env_int(ENV_SIGTERM_STEP)
    _state.sigterm_rank = _env_int(ENV_CHAOS_RANK)
    _state.stall_step = _env_int(ENV_STALL_STEP)
    _state.stall_s = float(os.environ.get(ENV_STALL_S, "0") or 0)
    _state.stall_until = None       # programmatic-only, never from env
    _state.nan_step = _env_int(ENV_NAN_STEP)


def configure(io_fail_writes: int = None, sigterm_step: int = None,
              sigterm_rank: int = None, stall_step: int = None,
              stall_s: float = None, nan_step: int = None,
              io_fail_reads: int = None, stall_until=None) -> None:
    """Programmatic arming (in-process tests); only the passed points move."""
    if stall_until is not None:
        _state.stall_until = stall_until
    if io_fail_writes is not None:
        _state.io_fail_writes = int(io_fail_writes)
    if io_fail_reads is not None:
        _state.io_fail_reads = int(io_fail_reads)
    if sigterm_step is not None:
        _state.sigterm_step = int(sigterm_step)
    if sigterm_rank is not None:
        _state.sigterm_rank = int(sigterm_rank)
    if stall_step is not None:
        _state.stall_step = int(stall_step)
    if stall_s is not None:
        _state.stall_s = float(stall_s)
    if nan_step is not None:
        _state.nan_step = int(nan_step)


def reset() -> None:
    """Disarm every injection point (does NOT touch os.environ)."""
    global _state
    _state = _State()


def armed() -> bool:
    return bool(_state.io_fail_writes or _state.io_fail_reads
                or _state.sigterm_step is not None
                or _state.stall_step is not None
                or _state.nan_step is not None)


# ------------------------------------------------------------------- points

def io_point(name: str = "ckpt_write") -> None:
    """Storage-write injection point: raises IOError while armed writes
    remain.  checkpoint._ChunkedWriter.finish calls this once per file."""
    if _state.io_fail_writes > 0:
        _state.io_fail_writes -= 1
        logger.warning("chaos: injected IO failure at %s (%d more armed)",
                       name, _state.io_fail_writes)
        raise IOError(f"chaos: injected IO failure at {name}")


#: read_point runs on restore-pool reader THREADS — the decrement must be
#: atomic or the armed count drifts (two readers both seeing 1)
_read_lock = threading.Lock()


def read_point(name: str = "ckpt_read") -> None:
    """Storage-read injection point: raises IOError while armed reads
    remain.  checkpoint._read_part calls this once per restore chunk, on
    whichever thread (serial caller or pool reader) performs the read."""
    if _state.io_fail_reads > 0:
        with _read_lock:
            if _state.io_fail_reads <= 0:
                return
            _state.io_fail_reads -= 1
            remaining = _state.io_fail_reads
        logger.warning("chaos: injected IO read failure at %s (%d more "
                       "armed)", name, remaining)
        raise IOError(f"chaos: injected IO read failure at {name}")


def step_point(step: int, rank: int = 0) -> None:
    """Step-boundary injection point (driver.run_resumable, before the
    step's work): SIGTERM-to-self at the armed step/rank."""
    if (_state.sigterm_step is not None and step == _state.sigterm_step
            and (_state.sigterm_rank is None or rank == _state.sigterm_rank)):
        _state.sigterm_step = None      # one shot
        logger.warning("chaos: SIGTERM self at step %d (rank %d)", step, rank)
        os.kill(os.getpid(), signal.SIGTERM)


def maybe_stall(step: int) -> None:
    """Collective-stall injection point: called by the ENGINE inside the
    watchdog-armed boundary region (step()/train_batch), so an armed stall
    is indistinguishable from a hung collective to the watchdog — the
    dump must name ``chaos_stall``."""
    if _state.stall_step is not None and step == _state.stall_step:
        _state.stall_step = None        # one shot
        until, _state.stall_until = _state.stall_until, None
        chaos_stall(_state.stall_s, until=until)


class _AnyEvent:
    """Composite stall-ender for multi-replica processes: only the
    replica that actually stalls has a watchdog that will fire, so the
    stall ends when ANY registered event sets."""

    def __init__(self, events):
        self.events = list(events)

    def is_set(self) -> bool:
        return any(e.is_set() for e in self.events)


def add_stall_until(event) -> None:
    """Register an ADDITIONAL stall-ending event.  ``configure``
    replaces the event; a process hosting several replicas (each with
    its own watchdog) must instead accumulate them — the stall lands in
    whichever replica reaches the armed dispatch first, and only that
    replica's watchdog reacts."""
    cur = _state.stall_until
    if cur is None:
        _state.stall_until = event
    elif isinstance(cur, _AnyEvent):
        cur.events.append(event)
    else:
        _state.stall_until = _AnyEvent([cur, event])


def chaos_stall(seconds: float, until=None) -> None:
    """Burn wall-clock inside a frame named ``chaos_stall`` so a watchdog
    stack dump identifies the stuck site by name.  ``until`` (a
    ``threading.Event``) ends the stall early — tests use the watchdog's
    ``fire_event`` so the stall lasts exactly until the dump happened."""
    logger.warning("chaos: stalling %.2fs", seconds)
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        if until is not None and until.is_set():
            return
        time.sleep(0.02)


def nan_at(step: int) -> bool:
    """True when the armed non-finite-loss step is ``step`` (one shot)."""
    if _state.nan_step is not None and step == _state.nan_step:
        _state.nan_step = None
        return True
    return False


def poison_batch(batch):
    """NaN-poison every float leaf of a batch pytree (integer token leaves
    pass through) — loss and gradients go non-finite downstream, which the
    engine's NaN/Inf sentinel must absorb as a skipped step."""
    import numpy as np
    import jax

    def poison(leaf):
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating):
            return np.full_like(a, np.nan)
        return leaf

    logger.warning("chaos: poisoning batch with NaN float leaves")
    return jax.tree_util.tree_map(poison, batch)


reload_env()
