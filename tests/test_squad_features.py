"""SQuAD featurization unit contracts (deepspeed_tpu.squad).

Fast-tier pins for the host-side data path the model tier builds on:
window coverage (EVERY context token appears in some window, including
the stride-misaligned tail), gold-span mapping, and postprocess span→text
recovery.
"""

import numpy as np

from deepspeed_tpu import squad
from deepspeed_tpu.tokenization import BertTokenizer, train_wordpiece


def _pipeline(ctx, question, answer, seq_len, doc_stride):
    exs = [squad.Example(qas_id="q0", question=question, context=ctx,
                         answers=[answer], answer_start=ctx.index(answer))]
    vocab = train_wordpiece([ctx, question], vocab_size=96)
    tok = BertTokenizer(vocab)
    feats = squad.featurize(exs, tok, seq_len=seq_len,
                            doc_stride=doc_stride)
    return exs, tok, feats


def test_stride_misaligned_tail_is_covered():
    """A context whose length minus the window budget is NOT a multiple of
    doc_stride must still cover its tail tokens (an extra full-width
    window is emitted) — an answer at the very end stays answerable."""
    words = " ".join(f"filler{i}" for i in range(40))
    ctx = words + " the hidden answer sits here"
    exs, tok, feats = _pipeline(ctx, "where does the answer sit",
                                "here", seq_len=32, doc_stride=16)
    n_ctx = len(tok.tokenize(ctx))
    covered = set()
    for f in feats:
        for s in f.token_spans:
            if s is not None:
                covered.add(s)
    # every context token's span appears in some window
    assert len(covered) == len(set(tok.tokenize_with_offsets(ctx)[1])), (
        len(covered), n_ctx)
    assert any(f.has_answer for f in feats), "tail answer lost"
    # gold span maps back to the answer text through postprocess
    starts = np.array([f.start_position for f in feats])
    ends = np.array([f.end_position for f in feats])
    scores = np.array([1.0 if f.has_answer else -1.0 for f in feats])
    preds = squad.postprocess(exs, feats, starts, ends, scores)
    assert preds["q0"] == "here", preds


def test_single_window_short_context():
    ctx = "Paris is the capital of France"
    exs, _, feats = _pipeline(ctx, "what is the capital",
                              "Paris", seq_len=48, doc_stride=16)
    assert len(feats) == 1 and feats[0].has_answer
    ids, attn, tt, s, e = squad.batch_features(feats)
    assert ids.shape == (1, 48) and attn.shape == (1, 48)
    assert s[0] > 0 and e[0] >= s[0]
    # token_type: question segment 0, context segment 1 where attended
    assert tt[0][int(s[0])] == 1
