"""ZeRO-1 x tensor parallelism: optimizer-state partitioning within each
model shard's data-parallel group.

The reference builds parameter-parallel groups so ZeRO partitions optimizer
state across the DP ranks of each MP rank (/root/reference/deepspeed/pt/
deepspeed_light.py:63-77, _configure_zero_optimizer :520-531).  Here the same
layout is the [mp, local_padded] P('model','data') flat master; these tests
pin the semantics: identical trajectories to the non-ZeRO and mp=1 engines,
agreed overflow/clip decisions across shards, and parameter-parallel
sub-groups composed with MP — each [S, local] row block-tiled into dp/pps
sub-groups (pure-DP sub-groups in tests/test_zero_pps.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2
from deepspeed_tpu.parallel.topology import make_mesh

# composition tier: 30-85 s of shard_map compiles per test — runs in the
# full suite/CI, excluded from `-m fast` (VERDICT r2 weak #6)
pytestmark = pytest.mark.slow


VOCAB, SEQ = 64, 16


def tiny_gpt2():
    return GPT2.from_size("tiny", vocab_size=VOCAB, max_seq_len=SEQ,
                          num_layers=2, hidden_size=32, num_heads=4)


def lm_batch(batch_size, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, VOCAB, size=(batch_size, SEQ)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    labels[:, -1] = -1
    return toks, labels


def make_engine(mp, zero, **cfg_over):
    # ZeRO requires a low-precision compute dtype (fp16/bf16) like the
    # reference (deepspeed_config.py:388-389)
    cfg = {
        "train_batch_size": 8,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": zero,
        "fp16": {"enabled": True, "initial_scale_power": 8},
    }
    cfg.update(cfg_over)
    model = tiny_gpt2()
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(7)),
        mesh=make_mesh(model_parallel_size=mp))
    return engine


def run(mp, zero, steps=5, **cfg_over):
    engine = make_engine(mp, zero, **cfg_over)
    losses = []
    for i in range(steps):
        toks, labels = lm_batch(8, seed=i)
        loss = engine(toks, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses, engine


def test_zero_mp2_matches_nonzero_mp2():
    """ZeRO partitioning must not change the math at mp=2 (fp32)."""
    ref, _ = run(2, zero=False)
    got, _ = run(2, zero=True)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)


def test_zero_mp_parity_mp124():
    """Same data+init => same trajectory for zero at mp=1,2,4."""
    ref, _ = run(1, zero=True)
    for mp in (2, 4):
        got, _ = run(mp, zero=True)
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)


def test_zero_mp_clipping_parity():
    """Gradient clipping under zero+mp needs the replicated-leaf norm dedup:
    a wrong total norm gives a different clip factor and the trajectories
    diverge from mp=1."""
    ref, _ = run(1, zero=True, steps=6, gradient_clipping=0.05)
    got, _ = run(2, zero=True, steps=6, gradient_clipping=0.05)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)


def test_zero_mp_fp16_trains():
    losses, engine = run(2, zero=True, steps=6,
                         fp16={"enabled": True, "initial_scale_power": 8})
    assert all(np.isfinite(losses))
    assert engine.master_flat.ndim == 2
    assert engine.master_flat.shape[0] == 2


def test_zero_mp_fp16_overflow_agreement():
    """An inf produced by one micro-batch must skip the update on every
    model shard and halve the shared loss scale exactly once."""
    engine = make_engine(2, zero=True,
                         fp16={"enabled": True, "initial_scale_power": 4})
    toks, labels = lm_batch(8)
    loss = engine(toks, labels)
    engine.backward(loss)
    # poison the accumulated grads of ONE model-sharded leaf slice
    leaves, treedef = jax.tree_util.tree_flatten(engine._acc)
    poisoned = []
    done = False
    for leaf in leaves:
        if not done and leaf.ndim >= 2:
            arr = np.array(leaf)
            arr[tuple(0 for _ in arr.shape)] = np.inf
            leaf = jax.device_put(jnp.asarray(arr), leaf.sharding)
            done = True
        poisoned.append(leaf)
    engine._acc = jax.tree_util.tree_unflatten(treedef, poisoned)
    scale_before = engine.optimizer.cur_scale
    master_before = np.asarray(jax.device_get(engine.master_flat))
    engine.step()
    assert engine.optimizer.overflow
    assert engine.skipped_steps == 1
    # MEGATRON-variant FSM: hysteresis may absorb the first overflow, but the
    # scale must be agreed and never grow
    assert engine.optimizer.cur_scale in (scale_before, scale_before / 2)
    master_after = np.asarray(jax.device_get(engine.master_flat))
    np.testing.assert_array_equal(master_after, master_before)


def test_zero_mp_optimizer_state_roundtrip():
    _, engine = run(2, zero=True, steps=2)
    sd = jax.tree_util.tree_map(np.asarray, engine.optimizer.state_dict(),
                                is_leaf=lambda x: x is None)
    params_before = jax.tree_util.tree_map(np.asarray, engine.params)
    # perturb, then restore
    engine.master_flat = jax.device_put(
        jnp.zeros_like(engine.master_flat), engine.master_flat.sharding)
    engine.optimizer.load_state_dict(sd)
    params_after = jax.tree_util.tree_map(np.asarray, engine.params)
    for a, b in zip(jax.tree_util.tree_leaves(params_before),
                    jax.tree_util.tree_leaves(params_after)):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_zero_mp_train_batch_fused_parity():
    """The fused train_batch program agrees with the split API under
    zero+mp."""
    e1 = make_engine(2, zero=True)
    e2 = make_engine(2, zero=True)
    losses1, losses2 = [], []
    for i in range(4):
        toks, labels = lm_batch(8, seed=i)
        loss = e1(toks, labels)
        e1.backward(loss)
        e1.step()
        losses1.append(float(loss))
        losses2.append(float(e2.train_batch((toks, labels))))
    np.testing.assert_allclose(losses2, losses1, rtol=2e-3, atol=1e-3)


def test_pps_with_mp_trajectory_parity():
    """parameter_parallel_size=2 x mp=2 (VERDICT r3 item 9): each [S, local]
    row block-tiles into dp/pps sub-groups; the trajectory must match the
    full-DP partitioning and the flat master must carry the tiled width."""
    ref, _ = run(2, zero=True)
    got, engine = run(2, zero={"stage": 1, "parameter_parallel_size": 2})
    assert engine.zero_pps == 2 and engine.zero_repl == 2
    assert engine.master_flat.ndim == 2
    # row width = repl * padded (block-tiled sub-group layout)
    assert engine.master_flat.shape[1] == 2 * engine.flat_meta.padded
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)


def test_pps_with_mp_checkpoint_cross_topology(tmp_path):
    """Save under pps=2 x mp=2, resume under full-DP x mp=2 (and back):
    the per-row partitions re-tile for the restoring topology."""
    def make(pps):
        zero = {"stage": 1}
        if pps:
            zero["parameter_parallel_size"] = pps
        return make_engine(2, zero=zero)

    def train(engine, n, s0=0):
        out = []
        for i in range(n):
            toks, labels = lm_batch(8, seed=s0 + i)
            loss = engine(toks, labels)
            engine.backward(loss)
            engine.step()
            out.append(float(loss))
        return out

    ref = train(make(2), 6)
    saver = make(2)
    train(saver, 3)
    saver.save_checkpoint(str(tmp_path), tag="ppsmp")
    import os
    files = sorted(os.listdir(os.path.join(str(tmp_path), "ppsmp")))
    zero_files = [f for f in files if f.startswith("zero_pp_rank_")]
    # 2 distinct partitions x 2 mp ranks (replica blocks deduped)
    assert zero_files == [
        f"zero_pp_rank_{r}_mp_rank_{m:02d}optim_states.pt"
        for r in range(2) for m in range(2)] or zero_files == [
        f"zero_pp_rank_{r}_mp_rank_{m:02d}optim_states.pt"
        for m in range(2) for r in range(2)], zero_files

    for restore_pps in (2, None):     # same topology, then full-DP
        resumed = make(restore_pps)
        path, _ = resumed.load_checkpoint(str(tmp_path), tag="ppsmp")
        assert path is not None
        post = train(resumed, 3, s0=3)
        np.testing.assert_allclose(post, ref[3:], rtol=1e-5)


def test_parameter_parallel_size_full_dp_accepted():
    mesh = make_mesh(model_parallel_size=2)
    dp = mesh.shape["data"]
    engine = make_engine(2, zero={"stage": 1,
                                  "parameter_parallel_size": dp})
    assert engine.zero_enabled
