"""SQuAD-style fine-tune-to-F1 harness (BingBertSquad analog).

BASELINE.md's north star is wall-clock to *F1 parity*; the reference ships
a fine-tune suite asserting EM/F1 after a SQuAD run
(/root/reference/tests/model/BingBertSquad/BingBertSquad_run_func_test.py:14-30,
run_BingBertSquad.sh).  Synthetic answerable-span corpus here (real SQuAD
files wire through examples/bert/squad_finetune.py): the engine fine-tune
must reach high F1 and land within 1 point of a plain-JAX fp32 baseline.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu import metrics
from deepspeed_tpu.models import BertForQuestionAnswering
from deepspeed_tpu.ops import optim as optim_mod
from deepspeed_tpu.parallel.topology import make_mesh

VOCAB, SEQ, BATCH, STEPS = 128, 32, 16, 150


def model_fn():
    return BertForQuestionAnswering.from_size(
        "tiny", vocab_size=VOCAB, max_seq_len=SEQ, num_layers=2,
        hidden_size=64, num_heads=4)


def qa_batch(rng, batch=BATCH):
    """Answerable spans marked in-band: token 1 opens, token 2 closes."""
    ids = rng.integers(4, VOCAB, size=(batch, SEQ)).astype(np.int32)
    start = rng.integers(1, SEQ - 4, size=(batch,)).astype(np.int32)
    end = (start + 2).astype(np.int32)
    for b in range(batch):
        ids[b, start[b]] = 1
        ids[b, end[b]] = 2
    attn = np.ones_like(ids)
    tt = np.zeros_like(ids)
    return ids, attn, tt, start, end


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(0)
    train = [qa_batch(rng) for _ in range(STEPS)]
    eval_rng = np.random.default_rng(10_000)
    dev = [qa_batch(eval_rng, batch=32) for _ in range(4)]
    return train, dev


def evaluate_f1(model, params, dev):
    """EM/F1 over the dev set via the span-prediction path."""
    predict = metrics.make_span_predictor(model, params)
    agg = {"exact_match": 0.0, "f1": 0.0, "total": 0}
    for ids, attn, tt, start, end in dev:
        sl, el = predict(ids, attn, tt)
        ps, pe = metrics.best_spans(sl, el, attn, max_answer_len=8)
        r = metrics.evaluate_spans(ps, pe, start, end)
        w = r["total"]
        agg["exact_match"] += r["exact_match"] * w
        agg["f1"] += r["f1"] * w
        agg["total"] += w
    agg["exact_match"] /= agg["total"]
    agg["f1"] /= agg["total"]
    return agg


@pytest.fixture(scope="module")
def baseline_f1(corpus):
    """Plain-JAX fp32 Adam fine-tune of the same model/data."""
    train, dev = corpus
    model = model_fn()
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, jnp.float32),
        model.init_params(jax.random.PRNGKey(1)))
    opt = optim_mod.Adam(lr=2e-3)
    state = opt.init(params)
    mesh = make_mesh(model_parallel_size=1, devices=jax.devices()[:1])

    def local(params, state, *batch):
        loss, grads = jax.value_and_grad(
            lambda p: model.apply(p, *batch))(params)
        new_p, new_s = opt.update(params, grads, state, lr=2e-3)
        return new_p, new_s, loss

    rep = lambda t: jax.tree_util.tree_map(lambda _: P(), t)
    step = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(rep(params), rep(state)) + (P(),) * 5,
        out_specs=(rep(params), rep(state), P()), check_vma=False))
    for batch in train:
        params, state, _ = step(params, state, *batch)
    return evaluate_f1(model, params, dev)


def test_engine_finetune_reaches_baseline_f1(corpus, baseline_f1):
    """Engine fine-tune (bf16) F1 within 1 point of the fp32 baseline —
    the reference suite's pass criterion shape."""
    train, dev = corpus
    model = model_fn()
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": BATCH,
                "steps_per_print": 10 ** 6,
                "optimizer": {"type": "Adam", "params": {"lr": 2e-3}},
                "bf16": {"enabled": True}},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(1)),
        mesh=make_mesh(model_parallel_size=1))
    for batch in train:
        engine.train_batch(batch)
    got = evaluate_f1(model, engine.params, dev)
    assert baseline_f1["f1"] > 90.0, baseline_f1
    assert got["f1"] > baseline_f1["f1"] - 1.0, (got, baseline_f1)
    assert got["exact_match"] > baseline_f1["exact_match"] - 2.0, (
        got, baseline_f1)


def test_load_squad_midword_answer_offset(tmp_path):
    """Answers starting mid-word ('$400' with answer_start at the '4')
    must map to the containing split word, not the following one."""
    import importlib.util
    import json
    import os
    spec = importlib.util.spec_from_file_location(
        "squad_finetune", os.path.join(
            os.path.dirname(__file__), "..", "..", "examples", "bert",
            "squad_finetune.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    ctx = "It cost $400 million total"
    data = {"data": [{"paragraphs": [{"context": ctx, "qas": [
        {"id": "q0", "question": "how much",
         "answers": [{"text": "400", "answer_start": ctx.index("400")}]},
    ]}]}]}
    p = tmp_path / "mini.json"
    p.write_text(json.dumps(data))
    feats, answers, dropped = mod.load_squad(str(p), 32, mod.Vocab(64))
    assert dropped == 0 and len(feats) == 1
    ids, attn, tt, start, end = feats[0]
    ctx_words, off, _ = answers[0]
    # '$400' is context word 2; both span ends point at it
    assert start - off == 2 and end - off == 2


def test_metric_unit_semantics():
    """Metric math pinned: official text normalization + span overlap."""
    assert metrics.text_exact_match("The Cat!", "cat") == 1.0
    assert metrics.text_f1("the cat sat", "a cat") == pytest.approx(2 / 3)
    assert metrics.span_f1((3, 5), (3, 5)) == 1.0
    assert metrics.span_f1((3, 5), (5, 7)) == pytest.approx(1 / 3)
    assert metrics.span_f1((0, 1), (4, 5)) == 0.0
    sl = np.full((1, 8), -5.0)
    el = np.full((1, 8), -5.0)
    sl[0, 2] = 5.0
    el[0, 4] = 5.0
    ps, pe = metrics.best_spans(sl, el, max_answer_len=8)
    assert (ps[0], pe[0]) == (2, 4)
    # max_answer_len forbids the wide span; falls back to best short one
    ps, pe = metrics.best_spans(sl, el, max_answer_len=2)
    assert pe[0] - ps[0] < 2
