"""KV-cache management for the serving engine.

The cache is a pair of preallocated per-layer buffers stacked on the
layer axis — ``k``/``v``: ``[L, slots, capacity, n_local_heads, d]`` —
plus per-slot ``pos`` bookkeeping, living on device for the whole
serving session.  Two layouts (``inference.kv_layout``):

* ``paged`` (default): capacity is the per-request token budget rounded
  up to whole pages (``page_tokens``); positions never wrap, so
  incremental decode is EXACT vs a full-context re-forward up to the
  budget (the oracle contract, docs/inference.md).
* ``ring``: the cache row wraps (``pos % capacity``) — a sliding
  attention window of the last ``capacity`` tokens.  Exactness holds
  only while a request's length stays within capacity; beyond it the
  window is a documented approximation.

Sizing is ARITHMETIC, not trial-and-error: :func:`cache_bytes` is the
exact buffer cost, and :func:`plan_slots` solves for the slot count that
fits the active :class:`~deepspeed_tpu.analysis.profiles.BackendProfile`
HBM after weights — the PR 6 capacity-planner handoff.  The engine's
``plan_capacity()`` additionally walks the compiled prefill/decode
programs (analysis/memplan.py) so transients are predicted too.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import MODEL_AXIS

LAYOUTS = ("paged", "ring")


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    """Resolved shape of the serving KV cache on ONE model shard."""
    layers: int
    slots: int                   # concurrent decode slots
    capacity: int                # tokens per slot (page-rounded)
    kv_heads_local: int          # heads held by this model shard
    head_dim: int
    mp_size: int = 1             # model-parallel degree (global heads =
                                 # kv_heads_local * mp_size)
    dtype: object = jnp.bfloat16
    layout: str = "paged"
    page_tokens: int = 128

    def __post_init__(self):
        if self.layout not in LAYOUTS:
            raise ValueError(
                f"kv_layout must be one of {LAYOUTS}, got {self.layout!r}")
        if self.slots < 1 or self.capacity < 1:
            raise ValueError(
                f"KV cache needs slots >= 1 and capacity >= 1 (got "
                f"slots={self.slots}, capacity={self.capacity})")

    @property
    def ring(self) -> bool:
        return self.layout == "ring"

    @property
    def pages_per_slot(self) -> int:
        return -(-self.capacity // max(1, self.page_tokens))

    @property
    def global_shape(self):
        """Shape of the (mesh-global) k/v buffers — the heads dim carries
        every model shard's heads; shard_map hands each rank its slice."""
        return (self.layers, self.slots, self.capacity,
                self.kv_heads_local * self.mp_size, self.head_dim)


def round_to_pages(tokens: int, page_tokens: int) -> int:
    """Capacity rounded UP to whole pages (the allocation granularity)."""
    page_tokens = max(1, int(page_tokens))
    return -(-int(tokens) // page_tokens) * page_tokens


def cache_bytes(spec: KVCacheSpec) -> int:
    """Exact per-device bytes of the k + v buffers (pos bookkeeping is
    noise)."""
    per_tok = spec.kv_heads_local * spec.head_dim
    return (2 * spec.layers * spec.slots * spec.capacity * per_tok
            * np.dtype(spec.dtype).itemsize)


def plan_slots(layers: int, kv_heads_local: int, head_dim: int,
               capacity: int, dtype, *, hbm_bytes: int,
               weight_bytes: int, headroom_frac: float = 0.1,
               slot_cap: int = 256) -> int:
    """Max decode slots that fit: ``(HBM·(1-headroom) - weights) /
    per-slot-bytes``, capped at ``slot_cap`` (beyond a few hundred slots
    decode is MXU-bound, not memory-bound — more slots only add latency).
    Raises when not even one slot fits — a serving config that cannot
    hold a single request must fail at build, not OOM on the first
    prompt."""
    per_slot = (2 * layers * capacity * kv_heads_local * head_dim
                * np.dtype(dtype).itemsize)
    budget = int(hbm_bytes * (1.0 - headroom_frac)) - int(weight_bytes)
    slots = budget // per_slot if per_slot > 0 else 0
    if slots < 1:
        raise ValueError(
            f"KV cache does not fit: {weight_bytes / 2**30:.2f} GiB of "
            f"weights + {per_slot / 2**20:.1f} MiB per slot exceed "
            f"{hbm_bytes / 2**30:.2f} GiB HBM (headroom "
            f"{headroom_frac:.0%}) — lower max_tokens, quantize, or use "
            f"a bigger profile")
    return int(min(slots, slot_cap))


def init_cache(spec: KVCacheSpec):
    """Zeroed (mesh-global) cache state: ``{"k", "v", "pos"}``.
    ``pos[s]`` is slot s's NEXT absolute position (0 = empty); inactive
    slots keep pos frozen."""
    return {
        "k": jnp.zeros(spec.global_shape, spec.dtype),
        "v": jnp.zeros(spec.global_shape, spec.dtype),
        "pos": jnp.zeros((spec.slots,), jnp.int32),
    }


def cache_partition_specs():
    """Mesh shardings of the cache state: K/V shard their HEADS dim over
    the model axis (each tensor-parallel rank caches exactly the heads it
    computes); bookkeeping is replicated."""
    return {
        "k": P(None, None, None, MODEL_AXIS, None),
        "v": P(None, None, None, MODEL_AXIS, None),
        "pos": P(),
    }


def spec_from_model(model, mp_size: int, *, slots: int, max_tokens: int,
                    dtype, layout: str = "paged",
                    page_tokens: int = 128,
                    hbm_bytes: Optional[int] = None,
                    weight_bytes: int = 0) -> KVCacheSpec:
    """Build the cache spec for an engine-protocol LM: dims from the
    model's ``kv_cache_dims`` hook, capacity page-rounded, and — when
    ``slots`` is 0 ("auto") — the slot count solved against the profile's
    HBM via :func:`plan_slots`."""
    dims_fn = getattr(model, "kv_cache_dims", None)
    if dims_fn is None:
        raise ValueError(
            f"{type(model).__name__} does not expose kv_cache_dims(mp) — "
            f"KV-cached serving needs the per-shard (layers, kv_heads, "
            f"head_dim) declaration (models/gpt2.py)")
    layers, kv_heads_local, head_dim = dims_fn(mp_size)
    capacity = round_to_pages(max_tokens, page_tokens)
    if slots in (0, None):
        if hbm_bytes is None:
            raise ValueError(
                "inference.max_slots=0 (auto) needs a backend profile to "
                "size against — set analysis.profile (docs/inference.md)")
        slots = plan_slots(layers, kv_heads_local, head_dim, capacity,
                           dtype, hbm_bytes=hbm_bytes,
                           weight_bytes=weight_bytes)
    return KVCacheSpec(layers=layers, slots=int(slots), capacity=capacity,
                       kv_heads_local=kv_heads_local, head_dim=head_dim,
                       mp_size=int(mp_size), dtype=dtype, layout=layout,
                       page_tokens=page_tokens)


def cache_jax_shapes(spec: KVCacheSpec):
    """ShapeDtypeStructs of the (mesh-global) cache state (planner
    tracing)."""
    return {
        "k": jax.ShapeDtypeStruct(spec.global_shape, spec.dtype),
        "v": jax.ShapeDtypeStruct(spec.global_shape, spec.dtype),
        "pos": jax.ShapeDtypeStruct((spec.slots,), jnp.int32),
    }
