"""Step tracing: programmatic jax.profiler capture + hang capture.

Two capture paths share one :class:`Tracer`:

* scheduled window — config ``observability: {trace_dir, trace_start_step,
  trace_num_steps}`` captures ``[start, start + num)`` optimizer
  boundaries (range checks, so a checkpoint resume landing mid-window
  still traces the remainder — same contract as the legacy ``profile``
  section, which this supersedes; configuring both is a config error).
* hang capture — wired as the resilience watchdog's ``on_fire`` hook: when
  a hang deadline trips, the monitor thread records a short trace under
  ``<trace_dir>/hang_*`` before the optional abort, so a wedged run leaves
  a profile of what the host was doing, not just a stack dump.

:func:`annotate` provides the ``TraceAnnotation`` spans the engine wraps
around fwd/bwd/boundary/checkpoint — named ``dstpu/<span>`` in the trace
viewer.  Annotations are host-side markers, ~free when no trace is active.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

logger = logging.getLogger(__name__)

#: env spelling of the trace directory — how the launcher (``dst
#: --trace_dir``) hands the capture destination to every worker and
#: ``--max_restarts`` relaunch (same pattern as DSTPU_COMPILE_CACHE_DIR)
ENV_TRACE_DIR = "DSTPU_TRACE_DIR"

#: set while ANY programmatic capture is active (scheduled window, hang
#: capture, or the legacy engine profile window).  :func:`annotate` is a
#: no-op unless this is set: ``jax.profiler.start_trace`` BLOCKS while any
#: thread holds an open TraceAnnotation (measured on jax 0.4), so an
#: always-on span around a blocking engine call would deadlock the
#: watchdog's hang capture against the very hang it is trying to record.
_capture_active = threading.Event()


def note_capture_active(active: bool) -> None:
    """Profiler session bracket — called by every start/stop site (Tracer
    and the engine's legacy ``start_profile``/``stop_profile``)."""
    if active:
        _capture_active.set()
    else:
        _capture_active.clear()


def resolve_trace_dir(cfg_dir: Optional[str]) -> Optional[str]:
    """Config value beats the :data:`ENV_TRACE_DIR` fallback; multi-process
    runs get a per-process subdirectory so workers never clobber each
    other's capture files."""
    d = cfg_dir or os.environ.get(ENV_TRACE_DIR) or None
    if d is None:
        return None
    import jax
    if jax.process_count() > 1:
        d = os.path.join(d, f"proc{jax.process_index()}")
    return d


_prewarm_started = False


def _prewarm_python_tracer() -> None:
    """Import the profiler's lazy host-side dependency in the background.

    The FIRST ``jax.profiler.start_trace`` of a process triggers XLA's
    python tracer hook, which lazily imports
    ``tensorflow.python.profiler.trace`` — ~10 s when tensorflow is
    installed.  Paying that on the capture path would stall the scheduled
    window's first traced step (or worse, outlive a watchdog hang capture
    whose process aborts).  A Tracer pre-warms it on a daemon thread at
    construction; a capture arriving mid-import simply waits on the
    import lock instead of re-paying it."""
    global _prewarm_started
    if _prewarm_started:
        return
    _prewarm_started = True

    def _load():
        try:
            import tensorflow.python.profiler.trace  # noqa: F401
        except Exception:
            pass        # no tensorflow: the hook fails fast at capture

    threading.Thread(target=_load, daemon=True,
                     name="dstpu-trace-prewarm").start()


def annotate(span: str):
    """``with annotate("fwd"): ...`` — a ``dstpu/<span>`` TraceAnnotation
    while a capture is active, a nullcontext otherwise (see
    :data:`_capture_active`: an open annotation on ANY thread blocks
    ``start_trace``, so spans must never straddle a step that could hang
    before a capture begins)."""
    if not _capture_active.is_set():
        from contextlib import nullcontext
        return nullcontext()
    import jax
    return jax.profiler.TraceAnnotation(f"dstpu/{span}")


class Tracer:
    """Owns programmatic profiler capture for one engine.  Thread-safe:
    the scheduled window runs on the training thread, hang capture on the
    watchdog monitor thread — exactly one capture may be active."""

    def __init__(self, trace_dir: str, start_step: int = 0,
                 num_steps: int = 0, hang_capture_s: float = 1.0):
        self.trace_dir = trace_dir
        self.start_step = int(start_step)
        self.end_step = self.start_step + int(num_steps)
        self.hang_capture_s = float(hang_capture_s)
        self._lock = threading.Lock()
        self._active = None     # path of the active capture, or None
        self._window_path = None    # the SCHEDULED window's capture path
        self._window_done = False
        self._atexit = False
        _prewarm_python_tracer()

    # ----------------------------------------------------------- start/stop
    def _start(self, path: str) -> bool:
        import jax
        with self._lock:
            if self._active is not None:
                return False
            try:
                jax.profiler.start_trace(path)
            except Exception as e:
                logger.warning("trace capture could not start (%s): %s",
                               path, e)
                return False
            self._active = path
            note_capture_active(True)
        if not self._atexit:
            # flush the capture even if training ends inside the window
            import atexit
            atexit.register(self.stop)
            self._atexit = True
        logger.info("telemetry: trace capture started -> %s", path)
        return True

    def stop(self) -> Optional[str]:
        import jax
        with self._lock:
            path, self._active = self._active, None
            if path is None:
                return None
            note_capture_active(False)
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover - defensive
                logger.warning("trace capture stop failed: %s", e)
                return None
        logger.info("telemetry: trace capture stopped (%s)", path)
        return path

    # ------------------------------------------------------ scheduled window
    def maybe_window(self, global_step: int) -> None:
        """Boundary hook: start/stop the configured capture window."""
        if self.end_step <= self.start_step:
            return
        if (self._active is None and not self._window_done
                and self.start_step <= global_step < self.end_step):
            path = os.path.join(
                self.trace_dir, f"steps_{self.start_step}_{self.end_step}")
            if self._start(path):
                self._window_path = path
        elif (self._active is not None
                and self._active == self._window_path
                and global_step >= self.end_step):
            # stop only OUR scheduled capture: a concurrent watchdog hang
            # capture (self._active holds a hang_* path) must not be
            # truncated by the next boundary's bookkeeping
            self.stop()
            self._window_path = None
            self._window_done = True

    # ----------------------------------------------------------- hang capture
    def capture_hang(self, tag: str = "") -> Optional[str]:
        """Record a short host-side trace when the watchdog fires.  Runs on
        the monitor thread while the training thread is (by definition)
        stuck; returns the capture path, or None when a capture was
        already active or could not start."""
        path = os.path.join(
            self.trace_dir,
            f"hang_{tag or 'watchdog'}_{int(time.time())}")
        if not self._start(path):
            return None
        time.sleep(self.hang_capture_s)
        return self.stop()
