"""Multi-node launcher: ``dst <args> script.py <script args>``.

TPU-native analog of the reference CLI
(/root/reference/deepspeed/pt/deepspeed_run.py; shipped as ``bin/ds``):

* hostfile in MPI syntax ``worker-0 slots=4`` (reference fetch_hostfile
  :88-113) — on TPU **1 slot = 1 host process** (process-per-host, not
  per-chip; each process drives all local chips through jax.distributed)
  but multi-slot hosts are honored for CPU/virtual-device fleets.
* include/exclude filter DSL ``-i "worker-0@worker-2:0,2"`` (reference
  parse_inclusion_exclusion :116-205): ``@`` separates nodes, ``:`` splits
  host from a comma-separated slot list, no list = all slots.
* world info passed to per-node launchers as base64 JSON (reference
  encode_world_info :218-221).
* fan-out via pdsh when available, else plain ssh per host, else local
  subprocess (reference :290-332 w/ local fallback :233-240); environment
  propagation = allowlist prefixes + a ``.deepspeed_env`` file of extra
  exports (reference EXPORT_ENVS/DEEPSPEED_ENVIRONMENT_NAME :26-46,290-305).
"""

from __future__ import annotations

import argparse
import base64
import json
import logging
import os
import shlex
import shutil
import subprocess
import sys
from collections import OrderedDict

logger = logging.getLogger(__name__)

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["TPU_", "JAX_", "XLA_", "PYTHON", "PATH", "LD_", "DSTPU_",
               "NCCL"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
DEEPSPEED_ENVIRONMENT_PATHS = [".", os.path.expanduser("~")]
PDSH_MAX_FAN_OUT = 1024


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="dst: deepspeed_tpu multi-host launcher")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile path (MPI style: 'host slots=N')")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Include filter, e.g. 'worker-0@worker-1:0,2'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Exclude filter, same DSL as --include")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Limit to first N nodes of the resource pool")
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1,
                        dest="num_gpus",
                        help="Limit slots per node (parity alias: num_chips)")
    parser.add_argument("--master_port", type=int, default=29500,
                        help="Coordinator port for jax.distributed")
    parser.add_argument("--master_addr", type=str, default="",
                        help="Coordinator address; default = first host")
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=("pdsh", "ssh", "local"),
                        help="Fan-out backend")
    parser.add_argument("--max_restarts", type=int, default=0,
                        help="Per-node relaunch budget after restartable "
                             "exits (preemption drain / watchdog abort; "
                             "docs/resilience.md)")
    parser.add_argument("--restart_backoff", type=float, default=1.0,
                        help="Base seconds of the jittered exponential "
                             "restart backoff")
    parser.add_argument("--compile_cache_dir", type=str, default="",
                        help="Persistent jax compilation cache directory "
                             "exported to every worker (and every "
                             "--max_restarts relaunch) as "
                             "DSTPU_COMPILE_CACHE_DIR, so a restarted "
                             "process reuses the prior attempt's compiled "
                             "step programs (docs/resilience.md)")
    parser.add_argument("--trace_dir", type=str, default="",
                        help="Telemetry trace destination exported to "
                             "every worker (and every --max_restarts "
                             "relaunch) as DSTPU_TRACE_DIR: jax.profiler "
                             "capture windows and watchdog hang captures "
                             "land here, one subdirectory per process "
                             "(docs/observability.md)")
    parser.add_argument("--health_port", type=int, default=0,
                        help="Base port of the per-process live health "
                             "endpoints (/healthz /status /metrics), "
                             "exported to every worker (and every "
                             "--max_restarts relaunch) as "
                             "DSTPU_HEALTH_PORT; each worker serves on "
                             "base + its global rank, rank 0 additionally "
                             "carries the fleet view.  0 disables "
                             "(docs/observability.md)")
    parser.add_argument("--force_multi", action="store_true",
                        help="Treat a single-node pool as multi-node (ssh)")
    parser.add_argument("user_script", type=str,
                        help="User training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER,
                        help="User script arguments")
    return parser.parse_args(args=args)


# ------------------------------------------------------------------ hostfile

def fetch_hostfile(hostfile_path):
    """Parse 'hostname slots=N' lines; None when absent (reference
    fetch_hostfile :88-113)."""
    if not os.path.isfile(hostfile_path):
        logger.warning("no hostfile at %s — falling back to this machine's "
                       "local slots only", hostfile_path)
        return None
    resource_pool = OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd.readlines():
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                logger.error("hostfile line %r does not parse as "
                             "'<hostname> slots=<int>'", line)
                raise ValueError(f"hostfile bad entry: {line!r}")
            if hostname in resource_pool:
                logger.error("hostfile lists %s twice — each host may "
                             "appear on one line only", hostname)
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_hostfile_filter(filter_str):
    """'worker-0@worker-1:0,2' → OrderedDict(host → [slots] or [])"""
    mapping = OrderedDict()
    for node_config in filter_str.split("@"):
        node_config = node_config.strip()
        if node_config == "":
            continue
        if ":" in node_config:
            hostname, slots = node_config.split(":")
            slot_list = [int(x) for x in slots.split(",") if x != ""]
        else:
            hostname, slot_list = node_config, []
        if hostname in mapping:
            raise ValueError(f"host {hostname} defined twice in {filter_str!r}")
        mapping[hostname.strip()] = slot_list
    return mapping


def parse_resource_filter(host_info, include_str="", exclude_str=""):
    """Apply -i/-e to a resource pool (host → slot count), returning
    host → [slot ids].  Mutually exclusive; unknown hosts/slots are errors
    (reference parse_inclusion_exclusion + parse_resource_filter
    :116-205)."""
    if include_str != "" and exclude_str != "":
        raise ValueError("include_str and exclude_str are mutually exclusive")

    active = OrderedDict(
        (host, list(range(count))) for host, count in host_info.items())
    if include_str == "" and exclude_str == "":
        return active

    filter_str = include_str if include_str != "" else exclude_str
    mapping = _parse_hostfile_filter(filter_str)
    for hostname, slots in mapping.items():
        if hostname not in host_info:
            raise ValueError(f"unknown host {hostname!r} in filter")
        for s in slots:
            if s not in range(host_info[hostname]):
                raise ValueError(
                    f"unknown slot {s} on host {hostname!r} in filter")

    if include_str != "":
        filtered = OrderedDict()
        for hostname, slots in mapping.items():
            filtered[hostname] = (slots if slots
                                  else list(range(host_info[hostname])))
        return filtered

    # exclude
    filtered = OrderedDict()
    for hostname, all_slots in active.items():
        if hostname not in mapping:
            filtered[hostname] = all_slots
            continue
        dropped = mapping[hostname]
        if not dropped:           # whole host excluded
            continue
        keep = [s for s in all_slots if s not in dropped]
        if keep:
            filtered[hostname] = keep
    return filtered


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    return parse_resource_filter(dict(resource_pool),
                                 include_str=inclusion, exclude_str=exclusion)


# ---------------------------------------------------------------- world info

def encode_world_info(world_info) -> str:
    """base64(JSON) (reference encode_world_info :218-221)."""
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def decode_world_info(encoded: str):
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


# ---------------------------------------------------------------------- main

def _env_exports():
    exports = []
    for var, val in os.environ.items():
        if any(var.startswith(p) for p in EXPORT_ENVS):
            exports.append(f"export {var}={shlex.quote(val)}")
    for path in DEEPSPEED_ENVIRONMENT_PATHS:
        env_file = os.path.join(path, DEEPSPEED_ENVIRONMENT_NAME)
        if os.path.isfile(env_file):
            with open(env_file) as f:
                for line in f.readlines():
                    line = line.strip()
                    if line and not line.startswith("#"):
                        key, sep, val = line.partition("=")
                        exports.append(
                            f"export {key}={shlex.quote(val)}" if sep
                            else f"export {line}")
    return exports


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if resource_pool is None:
        # local-only fallback (reference :233-240): one process by default,
        # --num_gpus/--num_chips N requests N local slots
        n_slots = args.num_gpus if args.num_gpus > 0 else 1
        active = OrderedDict({"localhost": list(range(n_slots))})
        if args.include or args.exclude:
            raise ValueError(
                "include/exclude require a hostfile (no resource pool)")
        multi_node = args.force_multi
    else:
        active = parse_inclusion_exclusion(resource_pool, args.include,
                                           args.exclude)
        if args.num_nodes > 0:
            active = OrderedDict(list(active.items())[:args.num_nodes])
        if args.num_gpus > 0:
            active = OrderedDict(
                (h, s[:args.num_gpus]) for h, s in active.items())
        multi_node = len(active) > 1 or args.force_multi

    if not active:
        raise ValueError("no hosts remain after filtering")

    first_host = next(iter(active))
    master_addr = args.master_addr
    if not master_addr:
        if multi_node and first_host not in ("localhost", "127.0.0.1"):
            # reference resolves via `ssh first_host hostname -I` (:254-261)
            try:
                out = subprocess.check_output(
                    ["ssh", first_host, "hostname", "-I"], timeout=30)
                master_addr = out.decode().split()[0]
            except Exception:
                master_addr = first_host
        else:
            master_addr = "127.0.0.1"

    world_info = {h: s for h, s in active.items()}
    encoded = encode_world_info(world_info)

    launch_cmd = [
        sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
        f"--world_info={encoded}",
        f"--master_addr={master_addr}",
        f"--master_port={args.master_port}",
    ]
    if args.max_restarts:
        launch_cmd += [f"--max_restarts={args.max_restarts}",
                       f"--restart_backoff={args.restart_backoff}"]
    if args.compile_cache_dir:
        launch_cmd += [f"--compile_cache_dir={args.compile_cache_dir}"]
    if args.trace_dir:
        launch_cmd += [f"--trace_dir={args.trace_dir}"]
    if args.health_port:
        launch_cmd += [f"--health_port={args.health_port}"]

    if not multi_node:
        cmd = launch_cmd + ["--node_rank=0", args.user_script] + args.user_args
        logger.info("cmd=%s", cmd)
        result = subprocess.Popen(cmd, env=os.environ.copy())
        result.wait()
        return result.returncode

    exports = _env_exports()
    runner = args.launcher
    if runner == "pdsh" and shutil.which("pdsh") is None:
        logger.warning("pdsh not found, falling back to ssh fan-out")
        runner = "ssh"

    procs = []
    hosts = list(active.keys())
    if runner == "pdsh":
        env = os.environ.copy()
        env["PDSH_RCMD_TYPE"] = "ssh"
        host_list = ",".join(hosts)
        # %n expands to the pdsh node rank on each target
        remote_cmd = (
            "; ".join(exports + [f"cd {shlex.quote(os.path.abspath(os.getcwd()))}"])
            + "; " + " ".join(map(shlex.quote, launch_cmd))
            + " --node_rank=%n " + shlex.quote(args.user_script) + " "
            + " ".join(map(shlex.quote, args.user_args)))
        cmd = ["pdsh", "-f", str(PDSH_MAX_FAN_OUT), "-w", host_list,
               remote_cmd]
        logger.info("cmd=%s", cmd)
        procs.append(subprocess.Popen(cmd, env=env))
    else:
        for rank, host in enumerate(hosts):
            remote_cmd = (
                "; ".join(exports + [f"cd {os.path.abspath(os.getcwd())}"])
                + "; " + " ".join(launch_cmd)
                + f" --node_rank={rank} " + args.user_script + " "
                + " ".join(args.user_args))
            cmd = ["ssh", host, remote_cmd]
            logger.info("cmd=%s", cmd)
            procs.append(subprocess.Popen(cmd, env=os.environ.copy()))

    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    sys.exit(main())
