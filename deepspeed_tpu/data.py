"""Data loading: host-side batching + device placement over the data axis.

TPU-native analog of /root/reference/deepspeed/pt/deepspeed_dataloader.py:
``DeepSpeedDataLoader`` there wraps a torch DataLoader with an automatic
``DistributedSampler`` (one shard of every batch per DP rank, :23-31) and hooks
the throughput timer on ``__next__`` (:53-56).  Here the loader produces the
*global* batch as a ``jax.Array`` sharded over the mesh's ``data`` axis — each
device receives only its shard, which is the DistributedSampler contract
expressed as sharding instead of per-rank iteration.

Dataset protocol: anything indexable with ``len()`` whose items are pytrees of
numpy-convertible leaves (tuples, dicts, arrays); or a pytree of full arrays
with a leading sample axis.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Callable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.constants import ROUTE_TRAIN
from deepspeed_tpu.parallel.topology import DATA_AXIS

logger = logging.getLogger(__name__)


def default_collate(samples):
    """Stack a list of pytree samples into a batch pytree (np.stack per leaf)."""
    first = samples[0]
    return jax.tree_util.tree_map(lambda *leaves: np.stack(leaves), first,
                                  *samples[1:])


def _iter_prefetched(items: Iterator[Any], depth: int, name: str):
    """Producer-thread prefetch: drain ``items`` on a daemon thread,
    keeping up to ``depth`` of them ready for the consumer (the torch
    DataLoader worker analog) — the ONE owner of the queue/sentinel/
    exception-forwarding machinery ``DeepSpeedDataLoader`` (per batch)
    and ``BlockPrefetcher`` (per K-block) share.  Abandoning the
    returned iterator early (break / GC) signals the producer to exit
    instead of leaving it blocked on a full queue; a producer exception
    re-raises in the consumer."""
    q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
    stop = threading.Event()
    SENTINEL = object()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        try:
            for item in items:
                if not put(item):
                    return
            put(SENTINEL)
        except BaseException as e:  # surface in the consumer
            put(e)

    t = threading.Thread(target=produce, daemon=True, name=name)
    t.start()
    try:
        while True:
            item = q.get()
            if item is SENTINEL:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        t.join()


class DeepSpeedDataLoader:
    """Sharded batch iterator.

    Args:
      dataset: indexable dataset (see module docstring).
      batch_size: GLOBAL batch per step (= micro_batch_per_rank * dp_size),
        matching the reference where the sampler splits each global batch
        across ranks.
      mesh: engine mesh; batches are sharded over its ``data`` axis.  None =>
        host-local numpy batches (no device placement), useful for tests.
      route: 'train' shuffles each epoch (RandomSampler/DistributedSampler
        shuffle); other routes are sequential (reference
        deepspeed_light.py:546-556 uses SequentialSampler for eval/predict).
      tput_timer: optional ThroughputTimer; ``start()`` is called on every
        ``__next__`` like the reference hooks it (deepspeed_dataloader.py:53-56).
      drop_last: drop the trailing ragged batch (default True: global batches
        must be shardable over the data axis).
    """

    def __init__(self,
                 dataset,
                 batch_size: int,
                 mesh: Optional[Mesh] = None,
                 route: str = ROUTE_TRAIN,
                 collate_fn: Optional[Callable] = None,
                 tput_timer=None,
                 seed: int = 0,
                 drop_last: bool = True,
                 local_rank: int = -1,
                 num_workers: int = 0,
                 prefetch_depth: int = 2,
                 device_prefetch: bool = False):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.mesh = mesh
        self.route = route
        self.collate_fn = collate_fn or default_collate
        self.tput_timer = tput_timer
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self.local_rank = local_rank
        # num_workers > 0 enables background prefetch (the reference defaults
        # to 2 x device_count torch DataLoader workers,
        # deepspeed_dataloader.py:33-34; here one producer thread overlaps
        # collation — itself multithreaded in C for array datasets — with
        # device compute, queue depth = prefetch_depth)
        self.num_workers = int(num_workers)
        self.prefetch_depth = max(1, int(prefetch_depth))
        self.device_prefetch = bool(device_prefetch)

        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        n = len(dataset)
        if drop_last:
            self.len = n // self.batch_size
        else:
            self.len = (n + self.batch_size - 1) // self.batch_size
        self._sharding = None
        if mesh is not None:
            self._sharding = NamedSharding(mesh, P(DATA_AXIS))
        # resumable-iterator position (docs/resilience.md): batches YIELDED
        # in the current epoch, and the skip count the next __iter__ honours
        # after load_state_dict
        self._batch_pos = 0
        self._resume_pos = 0

    def set_epoch(self, epoch: int) -> None:
        """DistributedSampler.set_epoch equivalent: reseeds the shuffle."""
        self.epoch = int(epoch)

    # ------------------------------------------------------- resume state

    def state_dict(self) -> dict:
        """Snapshot the iterator position: epoch, batches consumed within
        it, and the shuffle seed (the RNG key — each epoch's permutation is
        ``default_rng(seed + epoch)``, so (seed, epoch, batch) pins the
        exact sample stream).  Taken at a step boundary it makes the
        loader resumable mid-epoch: a fresh loader given this dict yields
        exactly the batches the interrupted run never consumed
        (resilience.run_resumable stores it in every checkpoint's
        client_state)."""
        return {"epoch": int(self.epoch), "batch": int(self._batch_pos),
                "seed": int(self.seed)}

    def load_state_dict(self, sd: dict) -> None:
        self.epoch = int(sd["epoch"])
        self.seed = int(sd.get("seed", self.seed))
        pos = int(sd["batch"])
        if not 0 <= pos <= self.len:
            raise ValueError(
                f"data iterator state batch={pos} is outside this loader's "
                f"epoch ({self.len} batches) — different dataset or "
                f"batch size than the saving run?")
        self._resume_pos = pos
        self._batch_pos = pos

    def _indices(self) -> np.ndarray:
        n = len(self.dataset)
        if self.route == ROUTE_TRAIN:
            rng = np.random.default_rng(self.seed + self.epoch)
            return rng.permutation(n)
        return np.arange(n)

    def _place(self, batch):
        """Shard the stacked numpy batch over the data axis.

        Multi-process, placement goes through ``make_array_from_callback``
        — each process fills only its addressable shards from the batch
        it already holds, with ZERO collectives.  A multi-host
        ``jax.device_put`` of a host value runs per-leaf cross-host
        consistency collectives instead (the PR 4 checkpoint-restore
        lesson, found again here standing up the 2-process observability
        smoke: the per-batch gloo ops interleave with the training
        collectives on the shared TCP pair and corrupt the stream —
        ``op.preamble.length <= op.nbytes`` aborts)."""
        if self._sharding is None:
            return batch
        multi_host = jax.process_count() > 1

        def put(leaf):
            leaf = np.asarray(leaf)
            spec = P(DATA_AXIS) if leaf.ndim >= 1 else P()
            sharding = NamedSharding(self.mesh, spec)
            if multi_host:
                return jax.make_array_from_callback(
                    leaf.shape, sharding, lambda idx, l=leaf: l[idx])
            return jax.device_put(leaf, sharding)

        return jax.tree_util.tree_map(put, batch)

    def __len__(self) -> int:
        return self.len

    def _make_batch(self, sel: np.ndarray):
        """Collate one batch: datasets exposing the ``collate_gather``
        protocol (ArrayDataset) gather rows through the native multithreaded
        memcpy kernel; generic datasets take the per-sample python path.
        The distinct protocol name avoids hijacking unrelated ``gather``
        methods (e.g. torch.Tensor.gather)."""
        gather = getattr(self.dataset, "collate_gather", None)
        if gather is not None and self.collate_fn is default_collate:
            return gather(sel)
        samples = [self.dataset[int(i)] for i in sel]
        return self.collate_fn(samples)

    def _batches(self, idx: np.ndarray, start: int = 0):
        for b in range(start, self.len):
            yield self._make_batch(idx[b * self.batch_size:
                                       (b + 1) * self.batch_size])

    def _prefetched(self, idx: np.ndarray, start: int = 0):
        """Producer thread keeps up to ``prefetch_depth`` collated batches
        ready while the device computes (see :func:`_iter_prefetched`)."""
        def produced():
            for batch in self._batches(idx, start):
                # device placement on the producer: jax.device_put is
                # async (returns after enqueueing the DMA), so with
                # queue depth >= 2 the NEXT batch's host->device copy
                # overlaps the current step's compute — double
                # buffering (VERDICT r4 weak #4)
                yield (self._place(batch) if self.device_prefetch
                       else batch)

        return _iter_prefetched(produced(), self.prefetch_depth,
                                "dstpu-io-prefetch")

    def __iter__(self) -> Iterator[Any]:
        idx = self._indices()
        # honour a restored mid-epoch position exactly once: the epoch's
        # permutation is (seed, epoch)-deterministic, so skipping the first
        # `start` batches replays the interrupted epoch's remainder
        start = self._resume_pos
        self._resume_pos = 0
        self._batch_pos = start
        if self.num_workers > 0:
            # collation (and, with device_prefetch, the host->device copy)
            # runs concurrently on the producer; the timed span covers
            # dequeue (+ placement only when device_prefetch is off)
            for batch in self._prefetched(idx, start):
                if self.tput_timer is not None:
                    self.tput_timer.start()
                self._batch_pos += 1
                yield (batch if self.device_prefetch
                       else self._place(batch))
        else:
            # synchronous path: collation stays inside the timed span, like
            # the reference hooking the timer in __next__
            for b in range(start, self.len):
                if self.tput_timer is not None:
                    self.tput_timer.start()
                batch = self._make_batch(idx[b * self.batch_size:
                                             (b + 1) * self.batch_size])
                self._batch_pos += 1
                yield self._place(batch)
        self.epoch += 1
        self._batch_pos = 0


class BlockPrefetcher:
    """Group a batch iterator into K-blocks for ``engine.train_many``,
    staging block i+1 on a producer thread while block i trains — the
    host side of the on-device multi-step driver (docs/features.md
    "Multi-step driver").

    Each yielded block is a LIST of K batches (the ``train_many``
    argument shape: separate per-step trees, not a stacked array — see
    ``engine._build_train_many`` for why stacking would break the
    bitwise parity contract).  With ``place`` given (e.g. a bound
    ``loader._place``) every batch is staged to device ON THE PRODUCER:
    ``device_put`` is async, so with ``depth >= 2`` the next block's K
    host→device copies overlap the current block's K fused steps —
    double buffering at block granularity.

    A trailing partial block (fewer than K batches left) is yielded
    as-is by default; ``drop_last=True`` discards it (a partial block
    compiles one extra K'-step program)."""

    def __init__(self, batch_iter, k: int, place: Optional[Callable] = None,
                 depth: int = 2, drop_last: bool = False):
        if k < 1:
            raise ValueError(f"BlockPrefetcher: k must be >= 1, got {k}")
        self.batch_iter = iter(batch_iter)
        self.k = int(k)
        self.place = place
        self.depth = max(1, int(depth))
        self.drop_last = bool(drop_last)
        self._consumed = False

    def _blocks(self):
        block = []
        for batch in self.batch_iter:
            if self.place is not None:
                batch = self.place(batch)
            block.append(batch)
            if len(block) == self.k:
                yield block
                block = []
        if block and not self.drop_last:
            yield block

    def __iter__(self) -> Iterator[list]:
        # one-shot: the upstream iterator is consumed by the producer
        # thread; a second iteration would race a fresh producer against
        # any still-draining first one over the same iterator — fail
        # loudly instead of yielding nondeterministic block membership
        if self._consumed:
            raise RuntimeError(
                "BlockPrefetcher is one-shot: its upstream batch "
                "iterator is already (being) consumed — construct a new "
                "prefetcher over a fresh iterator")
        self._consumed = True
        return _iter_prefetched(self._blocks(), self.depth,
                                "dstpu-block-prefetch")


class FileDataset:
    """Memmap-backed pre-tokenized binary dataset: one ``<name>.npy`` per
    field plus a ``manifest.json`` recording field order (VERDICT r4 weak
    #4 — the file-backed real-data path).  Rows stream from disk through
    the same ``collate_gather`` fast path as ``ArrayDataset`` (the native
    row-gather reads straight out of the page cache); nothing is loaded
    up front, so the dataset size is bounded by disk, not host RAM.

    Write side: ``FileDataset.save(dir, ids=..., mask=...)`` (np.save per
    field).  The MLM builder in ``deepspeed_tpu.tokenization``
    (``build_mlm_arrays``) produces the exact field set the BERT
    pretraining bench consumes."""

    def __init__(self, directory: str):
        import json
        import os
        self.directory = directory
        with open(os.path.join(directory, "manifest.json")) as f:
            self.fields = json.load(f)["fields"]
        self.arrays = [np.load(os.path.join(directory, f"{name}.npy"),
                               mmap_mode="r") for name in self.fields]
        n = len(self.arrays[0])
        if any(len(a) != n for a in self.arrays):
            raise ValueError("fields disagree on the sample count")
        self.n = n

    @staticmethod
    def save(directory: str, **fields) -> str:
        import json
        import os
        os.makedirs(directory, exist_ok=True)
        names = list(fields)
        for name in names:
            np.save(os.path.join(directory, f"{name}.npy"),
                    np.ascontiguousarray(fields[name]))
        with open(os.path.join(directory, "manifest.json"), "w") as f:
            json.dump({"fields": names}, f)
        return directory

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        out = tuple(np.asarray(a[i]) for a in self.arrays)
        return out if len(out) > 1 else out[0]

    def collate_gather(self, indices):
        # gather_rows' ascontiguousarray sees a contiguous memmap and
        # takes a zero-copy view: rows stream from the page cache
        from deepspeed_tpu import native
        out = tuple(native.gather_rows(a, indices) for a in self.arrays)
        return out if len(out) > 1 else out[0]


class ArrayDataset:
    """Adapter: a pytree of arrays with leading sample axis -> indexable
    dataset (the reference tests build tensor datasets the same way,
    tests/unit/simple_model.py:44-52).  Batch collation goes through the
    native row-gather kernel (``deepspeed_tpu.native``) when available."""

    def __init__(self, *arrays):
        self.arrays = [np.ascontiguousarray(a) for a in arrays]
        n = len(self.arrays[0])
        if any(len(a) != n for a in self.arrays):
            raise ValueError("all arrays must share the leading dimension")
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        out = tuple(a[i] for a in self.arrays)
        return out if len(out) > 1 else out[0]

    def collate_gather(self, indices):
        """Collated batch for an index vector (the DataLoader fast path)."""
        from deepspeed_tpu import native
        out = tuple(native.gather_rows(a, indices) for a in self.arrays)
        return out if len(out) > 1 else out[0]
