"""Multi-node launcher (``dst``): hostfile + include/exclude DSL + per-node
process spawn.  Analog of /root/reference/deepspeed/pt/deepspeed_run.py and
deepspeed_launch.py (shipped as bin/ds, bin/ds_ssh)."""
