#!/usr/bin/env bash
# Create the TPU-VM slice described in tpu_config.json
# (reference analog: azure/create_vms.sh).
source "$(dirname "$0")/common.sh"

${GC} create "${TPU_NAME}" "${GFLAGS[@]}" \
    --accelerator-type "${ACCEL}" \
    --version "${RUNTIME}"

echo "created ${TPU_NAME} (${ACCEL}) in ${ZONE}"
${GC} describe "${TPU_NAME}" "${GFLAGS[@]}" --format='value(state)'
