"""Per-node launcher: decode world info, export the rendezvous contract,
spawn the user script.

Analog of /root/reference/deepspeed/pt/deepspeed_launch.py:56-119, with the
process model changed for TPU: the reference spawns one subprocess per local
GPU with ``--local_rank=i`` and CUDA_VISIBLE_DEVICES; a TPU host runs ONE
process that drives all local chips, so the global rank mapping is
slot-granular only for CPU/virtual fleets.  Env contract exported to the
child (consumed by ``parallel.topology.init_distributed``):

    DSTPU_COORDINATOR     = master_addr:master_port   (≈ MASTER_ADDR/PORT)
    DSTPU_NUM_PROCESSES   = total process count       (≈ WORLD_SIZE)
    DSTPU_PROCESS_ID      = this process's rank       (≈ RANK)

``--local_rank`` is still appended to the child args for reference-CLI
parity.

Resilience: ``--max_restarts N`` relaunches this node's processes (with
jittered exponential backoff) when they exit with a restartable code — the
``resilience`` exit-code contract (43 = preemption drain after an emergency
checkpoint, 44 = watchdog abort; docs/resilience.md).  The relaunched
processes auto-resume via ``resilience.run_resumable``'s newest-valid-
checkpoint discovery.
"""

from __future__ import annotations

import argparse
import logging
import os
import random
import subprocess
import sys
import time

from deepspeed_tpu.launcher.run import decode_world_info
from deepspeed_tpu.observability.health import (ENV_HEALTH_PORT,
                                                ENV_REPLICA_GENERATION)
from deepspeed_tpu.observability.tracing import ENV_TRACE_DIR
from deepspeed_tpu.resilience import RESTARTABLE_EXIT_CODES
from deepspeed_tpu.utils.compile_cache import ENV_DIR as COMPILE_CACHE_ENV_DIR

logger = logging.getLogger(__name__)

#: backoff ceiling between restart attempts
RESTART_BACKOFF_CAP_S = 60.0


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="per-node process launcher")
    parser.add_argument("--node_rank", type=int, default=0,
                        help="Rank of this node in the world info")
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--world_info", type=str, required=True,
                        help="base64 JSON of host → slot list")
    parser.add_argument("--max_restarts", type=int, default=0,
                        help="Relaunch budget after restartable exits "
                             f"(codes {RESTARTABLE_EXIT_CODES}: preemption "
                             "drain / watchdog abort)")
    parser.add_argument("--restart_backoff", type=float, default=1.0,
                        help="Base seconds of the jittered exponential "
                             "restart backoff")
    parser.add_argument("--compile_cache_dir", type=str, default="",
                        help="Persistent jax compilation cache directory: "
                             "exported to every spawned worker (including "
                             "--max_restarts relaunches) as "
                             "DSTPU_COMPILE_CACHE_DIR so time-to-first-step "
                             "after a preemption is restore + cache read, "
                             "not restore + full recompile")
    parser.add_argument("--trace_dir", type=str, default="",
                        help="Telemetry trace destination exported to "
                             "every spawned worker (including relaunches) "
                             "as DSTPU_TRACE_DIR — the engine resolves it "
                             "when the config carries no "
                             "observability.trace_dir")
    parser.add_argument("--health_port", type=int, default=0,
                        help="Base health-endpoint port exported to every "
                             "spawned worker (including relaunches) as "
                             "DSTPU_HEALTH_PORT; each worker serves "
                             "/healthz /status /metrics on base + its "
                             "global rank")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def restart_delay_s(attempt: int, base: float,
                    cap: float = RESTART_BACKOFF_CAP_S,
                    rand=random.random) -> float:
    """Jittered exponential backoff: ``min(cap, base * 2**(attempt-1)) *
    uniform(0.5, 1.5)`` — jitter so a pod's nodes do not re-stampede the
    coordinator in lockstep (attempt is 1-based)."""
    return min(cap, base * (2.0 ** max(0, attempt - 1))) * (0.5 + rand())


def global_rank_mapping(world_info):
    """host → list of global process ranks (reference
    deepspeed_launch.py:81-91)."""
    mapping = {}
    rank = 0
    for host, slots in world_info.items():
        mapping[host] = list(range(rank, rank + len(slots)))
        rank += len(slots)
    return mapping


def _spawn_procs(args, local_ranks, world_size, node_host, generation=0):
    procs = []
    for local_rank, global_rank in enumerate(local_ranks):
        env = os.environ.copy()
        # restart ordinal for the /metrics replica_generation gauge: a
        # fleet router tells a RELAUNCHED worker (generation bumped,
        # uptime reset) from a live one (observability/health.py)
        env[ENV_REPLICA_GENERATION] = str(int(generation))
        env["DSTPU_COORDINATOR"] = f"{args.master_addr}:{args.master_port}"
        env["DSTPU_NUM_PROCESSES"] = str(world_size)
        env["DSTPU_PROCESS_ID"] = str(global_rank)
        # reference-compatible spellings
        env["MASTER_ADDR"] = args.master_addr
        env["MASTER_PORT"] = str(args.master_port)
        env["WORLD_SIZE"] = str(world_size)
        env["RANK"] = str(global_rank)
        env["LOCAL_RANK"] = str(local_rank)
        if args.compile_cache_dir:
            # every attempt (first launch AND each restart) lands in the
            # same persistent compilation cache — the engine's env
            # fallback (utils/compile_cache.resolve_dir) picks it up even
            # when the ds_config carries no compile_cache block
            env[COMPILE_CACHE_ENV_DIR] = args.compile_cache_dir
        if args.trace_dir:
            # same fallback pattern for trace captures (workers append a
            # per-process subdirectory — observability/tracing.py)
            env[ENV_TRACE_DIR] = args.trace_dir
        if args.health_port:
            # BASE port only: each worker offsets by its own global rank
            # (observability/health.resolve_health_port), so co-hosted
            # workers never fight over one socket
            env[ENV_HEALTH_PORT] = str(args.health_port)
        cmd = ([sys.executable, "-u", args.training_script]
               + args.training_script_args
               + [f"--local_rank={local_rank}"])
        logger.info("node %s rank %d: %s", node_host, global_rank, cmd)
        procs.append(subprocess.Popen(cmd, env=env))
    return procs


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    assert len(world_info) > 0, "empty world info"

    hosts = list(world_info.keys())
    node_host = hosts[args.node_rank]
    mapping = global_rank_mapping(world_info)
    local_ranks = mapping[node_host]
    world_size = sum(len(v) for v in mapping.values())

    attempt = 0
    while True:
        procs = _spawn_procs(args, local_ranks, world_size, node_host,
                             generation=attempt)
        rc = 0
        for p in procs:
            p.wait()
            rc = rc or p.returncode
        if rc == 0:
            return 0
        codes = sorted({p.returncode for p in procs})
        # restart only when EVERY failure is a restartable drain/abort —
        # a rank that crashed with a real error (code 1, segfault) would
        # crash again; burning the budget on it helps nobody
        restartable = all(c in RESTARTABLE_EXIT_CODES or c == 0
                          for c in codes)
        if not restartable or attempt >= args.max_restarts:
            if restartable and args.max_restarts > 0:
                logger.error(
                    "restart budget exhausted (%d) with exit codes %s",
                    args.max_restarts, codes)
            return rc
        attempt += 1
        delay = restart_delay_s(attempt, args.restart_backoff)
        logger.warning(
            "restartable exit codes %s: relaunching (attempt %d/%d) "
            "after %.1fs backoff", codes, attempt, args.max_restarts, delay)
        time.sleep(delay)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    sys.exit(main())
