"""Multi-group optimizer param_groups: per-group LRs addressable by the
LR schedules (the reference's torch param-group list; leaves are assigned by
pytree-path regex since functional pytrees carry no tensor identity).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.ops import optim as optim_mod


class TwoLeaf:
    def init_params(self, rng):
        return {"body": jnp.ones((8,)), "head": jnp.ones((8,))}

    def apply(self, params, x):
        # grad of every element is exactly 1
        return jnp.sum(params["body"]) + jnp.sum(params["head"]) + 0.0 * x.sum()


def make_engine(param_groups=None, **cfg_over):
    cfg = {
        "train_batch_size": 8,
        "steps_per_print": 10 ** 6,
        "optimizer": {"type": "SGD", "params": {"lr": 0.1}},
    }
    cfg.update(cfg_over)
    model = TwoLeaf()
    engine, opt, _, sched = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        param_groups=param_groups)
    return engine, opt, sched


def step_once(engine):
    x = np.ones((8, 4), np.float32)
    loss = engine(x)
    engine.backward(loss)
    engine.step()


def test_per_group_lrs_apply():
    engine, opt, _ = make_engine(
        param_groups=[{"params": "head", "lr": 0.01}])
    assert len(opt.param_groups) == 2
    assert opt.param_groups[0]["lr"] == 0.1      # default group
    assert opt.param_groups[1]["lr"] == 0.01     # 'head' group
    step_once(engine)
    body = np.asarray(engine.master["body"])
    head = np.asarray(engine.master["head"])
    # grad == 1 everywhere: delta is exactly -lr of the owning group
    np.testing.assert_allclose(body, 1.0 - 0.1, rtol=1e-6)
    np.testing.assert_allclose(head, 1.0 - 0.01, rtol=1e-6)


def test_scheduler_drives_groups_independently():
    """List-valued schedule params give each group its own LR trajectory
    (the reference's _format_param path)."""
    engine, opt, sched = make_engine(
        param_groups=[{"params": "head", "lr": 0.01}],
        scheduler={"type": "WarmupLR",
                   "params": {"warmup_min_lr": [0.0, 0.0],
                              "warmup_max_lr": [0.1, 0.01],
                              "warmup_num_steps": 10}})
    for _ in range(3):
        step_once(engine)
    lr0 = opt.param_groups[0]["lr"]
    lr1 = opt.param_groups[1]["lr"]
    assert 0 < lr1 < lr0 < 0.1
    np.testing.assert_allclose(lr0 / lr1, 10.0, rtol=1e-6)


def test_param_groups_from_config_json():
    """The pure-JSON spelling (optimizer.param_groups) matches the API
    path; an explicit initialize(param_groups=...) beats it."""
    engine, opt, _ = make_engine(
        optimizer={"type": "SGD", "params": {"lr": 0.1},
                   "param_groups": [{"params": "head", "lr": 0.01}]})
    assert len(opt.param_groups) == 2
    step_once(engine)
    np.testing.assert_allclose(np.asarray(engine.master["head"]),
                               1.0 - 0.01, rtol=1e-6)
    # explicit argument wins over the JSON spelling
    engine, opt, _ = make_engine(
        param_groups=[{"params": "head", "lr": 0.5}],
        optimizer={"type": "SGD", "params": {"lr": 0.1},
                   "param_groups": [{"params": "head", "lr": 0.01}]})
    assert opt.param_groups[1]["lr"] == 0.5
    with pytest.raises(DeepSpeedConfigError, match="list of group"):
        make_engine(optimizer={"type": "SGD", "params": {"lr": 0.1},
                               "param_groups": {"params": "head"}})


def test_group_assignment_first_match_wins():
    engine, opt, _ = make_engine(
        param_groups=[{"params": "head|body", "lr": 0.05},
                      {"params": "body", "lr": 0.5}])
    ids = engine._group_ids
    assert ids["head"] == 1 and ids["body"] == 1


def test_adam_groups_trajectory_matches_separate_lrs():
    """Adam with two groups == two single-group runs at those LRs."""
    def tail(lr_head):
        engine, _, _ = make_engine(
            param_groups=[{"params": "head", "lr": lr_head}],
            optimizer={"type": "Adam", "params": {"lr": 0.1}})
        for _ in range(3):
            step_once(engine)
        return (np.asarray(engine.master["body"]),
                np.asarray(engine.master["head"]))

    body_a, head_a = tail(0.01)
    body_b, head_b = tail(0.001)
    np.testing.assert_allclose(body_a, body_b, rtol=1e-6)   # same group-0 lr
    assert not np.allclose(head_a, head_b)


def test_train_batch_fused_with_groups():
    engine, _, _ = make_engine(param_groups=[{"params": "head", "lr": 0.01}])
    x = np.ones((8, 4), np.float32)
    engine.train_batch((x,))
    np.testing.assert_allclose(np.asarray(engine.master["head"]),
                               1.0 - 0.01, rtol=1e-6)


def test_zero_param_groups_per_element_lrs():
    """param_groups now compose with ZeRO (Adam family): hypers expand to
    per-ELEMENT vectors over the flat partition.  grad == 1 everywhere,
    so after one step each leaf moved by exactly its group's Adam step
    (~lr), and an lr=0 group must not move at all."""
    engine, opt, _ = make_engine(
        param_groups=[{"params": "head", "lr": 0.0}],
        zero_optimization=True,
        optimizer={"type": "Adam", "params": {"lr": 0.1}},
        bf16={"enabled": True})
    assert engine.zero_enabled and len(opt.param_groups) == 2
    step_once(engine)
    # read the leaves back through the flat master
    from deepspeed_tpu import zero as zero_mod
    flat = np.asarray(jax.device_get(engine.master_flat))
    tree = zero_mod.unflatten_tree(
        jnp.asarray(engine._untile_flat(flat)), engine.flat_meta)
    body = np.asarray(tree["body"])
    head = np.asarray(tree["head"])
    np.testing.assert_allclose(head, 1.0, atol=1e-7)        # lr 0: frozen
    np.testing.assert_allclose(body, 1.0 - 0.1, atol=1e-3)  # Adam ~ -lr


def test_zero_param_groups_match_nonzero_trajectory():
    """ZeRO x param_groups trajectory == the replicated engine with the
    same groups (partitioned per-element hypers are numerics-equal)."""
    def run(zero):
        cfg = dict(param_groups=[{"params": "head", "lr": 0.02,
                                  "weight_decay": 0.0}],
                   optimizer={"type": "AdamW",
                              "params": {"lr": 0.1, "weight_decay": 0.1}},
                   bf16={"enabled": True})
        if zero:
            cfg["zero_optimization"] = True
        engine, _, _ = make_engine(**cfg)
        for _ in range(3):
            step_once(engine)
        if zero:
            from deepspeed_tpu import zero as zero_mod
            flat = np.asarray(jax.device_get(engine.master_flat))
            tree = zero_mod.unflatten_tree(
                jnp.asarray(engine._untile_flat(flat)), engine.flat_meta)
        else:
            tree = engine.master
        return (np.asarray(tree["body"]), np.asarray(tree["head"]))

    b0, h0 = run(zero=False)
    b1, h1 = run(zero=True)
    np.testing.assert_allclose(b1, b0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(h1, h0, rtol=1e-5, atol=1e-6)


def test_zero_rejects_lamb_with_or_without_groups():
    """ZeRO stays Adam-family (the reference guard): LAMB's per-tensor
    trust ratio has no flat-partition form, groups or not."""
    with pytest.raises(DeepSpeedConfigError, match="Adam-family"):
        make_engine(param_groups=[{"params": "head", "lr": 0.01}],
                    zero_optimization=True,
                    optimizer={"type": "Lamb", "params": {"lr": 0.1}},
                    fp16={"enabled": True, "initial_scale_power": 8})


def test_zero_mp_param_groups_freeze_group():
    """ZeRO x MP x param_groups: the per-element gid vector spans the
    LOCAL [S, local] slices (identical per row), so an lr=0 group stays
    frozen even when its leaf is model-sharded (wte is vocab-parallel)."""
    from deepspeed_tpu.models import GPT2
    from deepspeed_tpu.parallel.topology import make_mesh

    def run(lr_wte):
        model = GPT2.from_size("tiny", vocab_size=64, max_seq_len=16,
                               num_layers=2, hidden_size=32, num_heads=4)
        engine, _, _, _ = deepspeed_tpu.initialize(
            config={"train_batch_size": 8, "steps_per_print": 10 ** 6,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": True,
                    "bf16": {"enabled": True}},
            model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(0)),
            param_groups=[{"params": "wte", "lr": lr_wte}],
            mesh=make_mesh(model_parallel_size=2))
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 64, size=(8, 16)).astype(np.int32)
        labels = np.roll(toks, -1, axis=1)
        for _ in range(2):
            engine.train_batch((toks, labels))
        return {k: np.asarray(v) for k, v in engine.params.items()
                if k in ("wte", "wpe")}

    frozen = run(0.0)
    moving = run(1e-3)
    init = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float32),
        GPT2.from_size("tiny", vocab_size=64, max_seq_len=16,
                       num_layers=2, hidden_size=32,
                       num_heads=4).init_params(jax.random.PRNGKey(0)))
    # lr=0 group: wte identical to init through the sharded flat master.
    # atol sits above bf16 cast granularity (~2e-4 at these magnitudes)
    # but well below the ~2e-3 drift of 2 Adam steps at the default lr —
    # a misaligned gid map fails here.
    np.testing.assert_allclose(frozen["wte"].astype(np.float32),
                               init["wte"], atol=1e-3)
    assert not np.allclose(moving["wte"].astype(np.float32), init["wte"],
                           atol=1e-4)
    # the default group trains in both runs
    assert not np.allclose(frozen["wpe"].astype(np.float32), init["wpe"],
                           atol=1e-4)


def test_entry_without_pattern_rejected():
    with pytest.raises(DeepSpeedConfigError, match="params"):
        make_engine(param_groups=[{"lr": 0.01}])


def test_unmatched_pattern_rejected():
    """A typo'd regex must fail fast, not silently govern nothing."""
    with pytest.raises(DeepSpeedConfigError, match="matches no"):
        make_engine(param_groups=[{"params": "haed", "lr": 0.01}])


def test_unsupported_group_keys_rejected():
    """Hypers beyond lr/betas/weight_decay are not plumbed; silently training
    with other hyperparameters than the facade displays would be worse than
    an error."""
    with pytest.raises(DeepSpeedConfigError, match="unsupported keys"):
        make_engine(param_groups=[{"params": "head", "lr": 0.01,
                                   "momentum": 0.5}])


def test_betas_rejected_for_betaless_optimizer():
    """SGD/RMSprop/Adagrad never read beta1/beta2; a group carrying 'betas'
    would display hyperparameters the update rule ignores."""
    with pytest.raises(DeepSpeedConfigError, match="does not consume betas"):
        make_engine(param_groups=[{"params": "head", "betas": (0.5, 0.9)}])


def test_per_group_weight_decay():
    """Decay-excluded group (the published BERT recipe shape: LayerNorm/bias
    at weight_decay=0, reference bert-pretraining.md:289-305)."""
    engine, opt, _ = make_engine(
        param_groups=[{"params": "head", "weight_decay": 0.0}],
        optimizer={"type": "SGD",
                   "params": {"lr": 0.1, "weight_decay": 0.1}})
    assert opt.param_groups[0]["weight_decay"] == 0.1
    assert opt.param_groups[1]["weight_decay"] == 0.0
    step_once(engine)
    # grad == 1 everywhere: body sees g + wd*p = 1.1, head sees plain 1.0
    np.testing.assert_allclose(np.asarray(engine.master["body"]),
                               1.0 - 0.1 * 1.1, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(engine.master["head"]),
                               1.0 - 0.1 * 1.0, rtol=1e-6)


class QuadLeaf:
    """loss = Σp²/2 per leaf: gradients equal the (heterogeneous,
    time-varying) parameters — constant-uniform-gradient models are
    DEGENERATE for these assertions (Adam's trajectory is beta-invariant
    under constant grads; LAMB's trust ratio cancels a uniform decay of a
    uniform tensor)."""

    def init_params(self, rng):
        return {"body": jnp.linspace(0.5, 1.5, 8),
                "head": jnp.linspace(-1.0, 1.0, 8)}

    def apply(self, params, x):
        return (0.5 * jnp.sum(params["body"] ** 2)
                + 0.5 * jnp.sum(params["head"] ** 2) + 0.0 * x.sum())


def make_quad_engine(param_groups, **cfg_over):
    cfg = {"train_batch_size": 8, "steps_per_print": 10 ** 6}
    cfg.update(cfg_over)
    model = QuadLeaf()
    engine, opt, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        param_groups=param_groups)
    return engine, opt


def test_per_group_betas_adam():
    """Per-group betas change the group's trajectory and only that group's
    (closes the OneCycle multi-group momentum gap, VERDICT r2 weak #4)."""
    def tail(betas_head):
        engine, _ = make_quad_engine(
            [{"params": "head", "betas": betas_head}],
            optimizer={"type": "Adam", "params": {"lr": 0.1}})
        for _ in range(3):
            step_once(engine)
        return (np.asarray(engine.master["body"]),
                np.asarray(engine.master["head"]))

    body_a, head_a = tail((0.5, 0.9))
    body_b, head_b = tail((0.9, 0.999))
    np.testing.assert_allclose(body_a, body_b, rtol=1e-6)
    assert not np.allclose(head_a, head_b)


def test_per_group_wd_lamb_trajectory():
    """LAMB per-group decay exclusion: only the excluded group's trajectory
    moves when its weight_decay changes (the 16K-batch BERT recipe depends
    on this, reference deepspeed_fused_lamb.py:77-100)."""
    def tail(wd_head):
        engine, _ = make_quad_engine(
            [{"params": "head", "weight_decay": wd_head}],
            optimizer={"type": "Lamb",
                       "params": {"lr": 0.02, "weight_decay": 0.01}},
            fp16={"enabled": True, "initial_scale_power": 8})
        for _ in range(3):
            step_once(engine)
        return (np.asarray(engine.master["body"]),
                np.asarray(engine.master["head"]))

    body_a, head_a = tail(0.0)
    body_b, head_b = tail(0.3)
    np.testing.assert_allclose(body_a, body_b, rtol=1e-6)
    assert not np.allclose(head_a, head_b)
