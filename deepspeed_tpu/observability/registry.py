"""MetricRegistry — the single exporter fan-out.

Before this layer the engine had three independent scalar-writing paths
(throughput logging, ``resilience/counters.py`` TensorBoard loops, the
compile-cache counters riding the same loop) and nothing machine-readable.
Now every producer registers a SOURCE — a callable returning
``{name: number}`` — and the registry emits one consistent snapshot per
report window to every attached SINK:

* :class:`TensorboardSink` — ``Train/<group>/<name>`` scalars through the
  engine's existing ``SummaryWriter`` (same tags the three legacy paths
  wrote, so dashboards keep working);
* :class:`JsonlSink` — one schema-versioned line per window
  (observability/schema.py), the artifact the CI smoke job validates and
  bench tooling diffs.

Sources are pulled at EMIT time (drain or boundary), never per step —
collection cost rides the report cadence, not the hot path.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

from deepspeed_tpu.observability import schema

logger = logging.getLogger(__name__)


class MetricRegistry:
    """Named metric sources fanned out to sinks (thread-safe: the spool
    drain callback runs on the runtime's callback thread)."""

    def __init__(self):
        self._sources: Dict[str, Callable[[], dict]] = {}
        self._sinks = []
        self._lock = threading.Lock()

    def register(self, group: str, source: Callable[[], dict]) -> None:
        """Register/replace the source for ``group`` (a callable returning
        a flat ``{name: number}`` dict, pulled at emit time)."""
        with self._lock:
            self._sources[group] = source

    def unregister(self, group: str) -> None:
        with self._lock:
            self._sources.pop(group, None)

    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def collect(self) -> Dict[str, dict]:
        """One snapshot of every source: ``{group: {name: value}}``.  A
        source that raises is skipped with a warning — observability must
        never take down training."""
        with self._lock:
            sources = dict(self._sources)
        out = {}
        for group, fn in sources.items():
            try:
                out[group] = dict(fn())
            except Exception as e:  # pragma: no cover - defensive
                logger.warning("telemetry source %r failed: %s", group, e)
        return out

    def counters_snapshot(self) -> dict:
        """Every source flattened to ``{"group/name": value}`` — the
        counter spelling both export cadences (window drain and legacy
        boundary) share."""
        out = {}
        for group, vals in self.collect().items():
            for name, val in vals.items():
                out[f"{group}/{name}"] = val
        return out

    def emit(self, event: dict, sample_count: Optional[int] = None) -> None:
        """Fan one window event (plus a fresh source snapshot) out to every
        sink.  ``event`` is the spool's window record; sinks receive it
        with ``counters`` filled from the collected snapshot."""
        event = dict(event)
        event.setdefault("counters", {}).update(self.counters_snapshot())
        with self._lock:
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink.emit(event, sample_count=sample_count)
            except Exception as e:  # pragma: no cover - defensive
                logger.warning("telemetry sink %r failed: %s",
                               type(sink).__name__, e)

    def close(self) -> None:
        with self._lock:
            sinks, self._sinks = list(self._sinks), []
        for sink in sinks:
            try:
                sink.close()
            except Exception:  # pragma: no cover - defensive
                pass


class TensorboardSink:
    """Window events as ``Train/*`` scalars through an existing
    SummaryWriter — the dedup target of the three legacy write loops.
    Scalar tags: window metrics under ``Train/Telemetry/*``, counter
    groups under ``Train/<Group>/<name>`` (``Train/Resilience/*`` keeps
    its PR 4/5 spelling, so existing dashboards keep working)."""

    #: window-event fields exported as Train/Telemetry/* scalars
    _WINDOW_FIELDS = ("loss", "loss_mean", "grad_norm", "loss_scale",
                      "skipped", "step_ms", "samples_per_sec", "mfu",
                      "measured_peak_hbm_gb", "hbm_drift",
                      "predicted_peak_hbm_gb", "predicted_boundary_ms",
                      "measured_boundary_ms", "boundary_drift")

    def __init__(self, writer):
        #: a SummaryWriter, or a zero-arg callable resolving one LIVE —
        #: the engine's writer may be replaced after construction (tests
        #: inject fakes; users wire writers late), so the sink must not
        #: capture a stale reference
        self._writer = writer

    @property
    def writer(self):
        w = self._writer
        return w() if callable(w) else w

    def emit(self, event: dict, sample_count: Optional[int] = None) -> None:
        writer = self.writer
        if writer is None:
            return
        x = sample_count if sample_count is not None else event["step"]
        for name in self._WINDOW_FIELDS:
            val = event.get(name)
            if val is not None:
                writer.add_scalar(f"Train/Telemetry/{name}",
                                  float(val), x)
        for key, val in event.get("counters", {}).items():
            group, _, name = key.partition("/")
            writer.add_scalar(
                f"Train/{group.capitalize()}/{name}", float(val), x)

    def close(self) -> None:
        pass        # the writer belongs to the engine


class JsonlSink:
    """One schema-stamped JSON line per window, flushed per emit (the file
    must be complete up to the last drained window when the process is
    preempted — the flush-on-drain contract the resilience driver relies
    on).  Lines that fail self-validation are still written but logged
    loudly: a schema bug must be visible in CI, not silently dropped."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")

    def emit(self, event: dict, sample_count: Optional[int] = None) -> None:
        event = dict(event)
        event["schema"] = schema.SCHEMA_ID
        event["version"] = schema.SCHEMA_VERSION
        event.setdefault("ts", time.time())
        # every schema field present (null when unmeasured): a missing
        # column and an unmeasured column are different facts
        for name in schema.FIELDS:
            event.setdefault(name, None)
        msg = schema.validate_event(event)
        if msg is not None:  # pragma: no cover - schema bug guard
            logger.error("telemetry event fails its own schema (%s): %r",
                         msg, event)
        self._f.write(json.dumps(event) + "\n")
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:  # pragma: no cover - defensive
            pass
