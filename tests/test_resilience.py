"""Resilience subsystem: preemption drain + auto-resume, NaN sentinel,
hang watchdog, storage retry, checkpoint discovery, launcher restarts.

The chaos tier (``-m chaos``; docs/resilience.md): every fault is injected
DETERMINISTICALLY (resilience.chaos) and every resume asserts *bitwise*
parity with an uninterrupted run — "it recovered" means "the trajectory is
the one that would have happened anyway".
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu import resilience
from deepspeed_tpu.checkpoint import find_latest_valid_tag, validate_tag
from deepspeed_tpu.data import ArrayDataset, DeepSpeedDataLoader
from deepspeed_tpu.resilience import (COUNTERS, PreemptionHandler,
                                      RESUME_EXIT_CODE, WATCHDOG_EXIT_CODE,
                                      Watchdog, chaos)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from simple_model import SimpleModel  # noqa: E402

pytestmark = pytest.mark.chaos

HIDDEN = 8

ZERO_CFG = {
    "train_batch_size": 8,
    "steps_per_print": 1000,
    "optimizer": {"type": "Adam", "params": {"lr": 0.02}},
    "fp16": {"enabled": True, "loss_scale": 128.0},
    "zero_optimization": True,
}


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Order-independence: every test starts with disarmed injection
    points, zeroed counters, and no leaked signal handlers."""
    chaos.reset()
    COUNTERS.reset()
    yield
    chaos.reset()
    COUNTERS.reset()


def _engine_factory(cfg):
    def factory():
        engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=HIDDEN),
                                        config=dict(cfg))
        return engine
    return factory


def _dataset(n=64, seed=0, dtype=np.float16):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, HIDDEN)).astype(dtype)
    y = rng.integers(0, HIDDEN, size=(n,)).astype(np.int32)
    return ArrayDataset(x, y)


def _loader(dataset, seed=3):
    return DeepSpeedDataLoader(dataset, batch_size=8, mesh=None, seed=seed)


def _split_step(engine, batch):
    loss = engine(*batch)
    engine.backward(loss)
    engine.step()
    return loss


from simple_model import master_bytes as _master_bytes  # noqa: E402


# ------------------------------------------------- preemption + auto-resume

def test_sigterm_drain_and_bitwise_resume(tmpdir):
    """SIGTERM mid-run → flag → boundary poll → emergency checkpoint →
    RESUME_EXIT_CODE; a relaunch (fresh engine + loader) auto-resumes —
    data-iterator state included — and finishes BITWISE identical to an
    uninterrupted run."""
    factory = _engine_factory(ZERO_CFG)
    dataset = _dataset()

    unbroken = resilience.run_resumable(
        factory, _split_step, steps=6,
        save_dir=str(tmpdir.join("unbroken")), data_loader=_loader(dataset))
    ref_bytes = _master_bytes(unbroken)

    save_dir = str(tmpdir.join("interrupted"))
    handler = PreemptionHandler(sentinel_file=str(tmpdir.join("nope")))
    chaos.configure(sigterm_step=3, sigterm_rank=0)
    try:
        with pytest.raises(SystemExit) as ei:
            resilience.run_resumable(factory, _split_step, steps=6,
                                     save_dir=save_dir,
                                     data_loader=_loader(dataset),
                                     handler=handler)
        assert ei.value.code == RESUME_EXIT_CODE
        # chaos fires BEFORE step 3's work: the drain lands after step 3
        # completes, i.e. at global step 4
        tag = find_latest_valid_tag(save_dir)
        assert tag is not None and tag.startswith("emergency/"), tag
        with open(os.path.join(save_dir, "latest")) as f:
            assert f.read().strip() == tag

        # "relaunch": fresh engine + fresh loader, same save_dir
        handler.clear()
        resumed = resilience.run_resumable(factory, _split_step, steps=6,
                                           save_dir=save_dir,
                                           data_loader=_loader(dataset),
                                           handler=handler)
    finally:
        handler.uninstall()
    assert resumed.global_steps == 6
    assert COUNTERS.preemptions >= 1 and COUNTERS.restarts == 1
    assert _master_bytes(resumed) == ref_bytes


def test_sentinel_file_drain(tmpdir):
    """The DSTPU_PREEMPT_FILE spelling: touching the sentinel requests the
    same drain as a signal, without racing signal delivery."""
    factory = _engine_factory(ZERO_CFG)
    dataset = _dataset()
    sentinel = str(tmpdir.join("preempt"))
    handler = PreemptionHandler(sentinel_file=sentinel)
    seen = []

    def step_and_touch(engine, batch):
        _split_step(engine, batch)
        seen.append(engine.global_steps)
        if len(seen) == 2:
            open(sentinel, "w").close()

    try:
        with pytest.raises(SystemExit) as ei:
            resilience.run_resumable(factory, step_and_touch, steps=6,
                                     save_dir=str(tmpdir.join("ck")),
                                     data_loader=_loader(dataset),
                                     handler=handler)
    finally:
        handler.uninstall()
    assert ei.value.code == RESUME_EXIT_CODE
    tag = find_latest_valid_tag(str(tmpdir.join("ck")))
    assert tag == "emergency/global_step2", tag


def test_periodic_saves_and_discovery(tmpdir):
    """save_interval checkpoints carry the data-iterator state and the
    newest one wins discovery."""
    factory = _engine_factory(ZERO_CFG)
    dataset = _dataset()
    save_dir = str(tmpdir.join("ck"))
    resilience.run_resumable(factory, _split_step, steps=5,
                             save_dir=save_dir, data_loader=_loader(dataset),
                             save_interval=2)
    assert validate_tag(save_dir, "global_step2")
    assert validate_tag(save_dir, "global_step4")
    assert find_latest_valid_tag(save_dir) == "global_step4"
    # the data-iterator snapshot rides in client_state
    engine = factory()
    _, client = engine.load_checkpoint(save_dir, tag="global_step4")
    assert client[resilience.DATA_ITER_KEY] == {
        "epoch": 0, "batch": 4, "seed": 3}


def test_resume_skips_half_written_tag(tmpdir):
    """A mid-save SIGKILL can leave a tag's model header durable but its
    ZeRO shard files missing — it then passes header-only validation, so
    the driver must exclude it after the full load fails and restore the
    next-newest valid tag instead of bricking every restart (and must
    RAISE, not silently train from scratch, when no candidate restores)."""
    import glob
    factory = _engine_factory(ZERO_CFG)
    dataset = _dataset()
    save_dir = str(tmpdir.join("ck"))
    resilience.run_resumable(factory, _split_step, steps=3,
                             save_dir=save_dir, data_loader=_loader(dataset),
                             save_interval=1)       # tags global_step1, 2
    for f in glob.glob(os.path.join(save_dir, "global_step2",
                                    "zero_pp_rank_*")):
        os.remove(f)                                 # half-written newest
    engine = factory()
    tag = resilience.restore_latest(engine, save_dir,
                                    io_retries=0)
    assert tag == "global_step1", tag
    assert engine.global_steps == 1
    # no restorable candidate at all -> raise (never silently restart)
    for f in glob.glob(os.path.join(save_dir, "global_step1",
                                    "zero_pp_rank_*")):
        os.remove(f)
    with pytest.raises(FileNotFoundError):
        resilience.restore_latest(factory(), save_dir, io_retries=0)


def test_discovery_mtime_tie_breaks_numerically(tmpdir):
    """Equal model-file mtimes (coarse-mtime FS, rsync'd dirs): the
    trailing STEP NUMBER breaks the tie, so global_step10 beats
    global_step9 even though '9' > '1' lexicographically."""
    factory = _engine_factory(ZERO_CFG)
    save_dir = str(tmpdir.join("ck"))
    engine = factory()
    for tag in ("global_step9", "global_step10"):
        engine.save_checkpoint(save_dir, tag=tag)
    probe = lambda t: os.path.join(save_dir, t, "mp_rank_00_model_states.pt")
    os.utime(probe("global_step9"), (1000.0, 1000.0))
    os.utime(probe("global_step10"), (1000.0, 1000.0))
    assert find_latest_valid_tag(save_dir) == "global_step10"


# ------------------------------------------------------------- NaN sentinel

NAN_CFG = {
    "train_batch_size": 8,
    "steps_per_print": 1000,
    "optimizer": {"type": "Adam", "params": {"lr": 0.02}},
    "resilience": {"nan_sentinel": True},
}


def _fp32_batch(i):
    rng = np.random.default_rng(100 + i)
    x = rng.normal(size=(8, HIDDEN)).astype(np.float32)
    y = rng.integers(0, HIDDEN, size=(8,)).astype(np.int32)
    return x, y


def test_nan_sentinel_skips_poisoned_step(tmpdir):
    """fp32 + nan_sentinel: a non-finite batch skips the boundary (master
    bitwise unchanged, no scheduler step, counter bumped) and training
    continues finite — the fp16 skip-on-overflow contract extended."""
    engine = _engine_factory(NAN_CFG)()
    _split_step(engine, _fp32_batch(0))
    before = _master_bytes(engine)

    x, y = _fp32_batch(1)
    _split_step(engine, chaos.poison_batch((x, y)))
    assert engine.overflow is True
    assert engine.skipped_steps == 1
    assert COUNTERS.nan_skips == 1
    assert _master_bytes(engine) == before          # boundary was a no-op

    loss = _split_step(engine, _fp32_batch(2))      # recovers immediately
    assert np.isfinite(float(loss))
    assert np.isfinite(np.frombuffer(_master_bytes(engine),
                                     np.float32)).all()


def test_without_sentinel_nan_poisons_params(tmpdir):
    """Negative control: the same poisoned batch WITHOUT the sentinel
    corrupts the fp32 master — proving the sentinel is load-bearing."""
    cfg = {k: v for k, v in NAN_CFG.items() if k != "resilience"}
    engine = _engine_factory(cfg)()
    _split_step(engine, _fp32_batch(0))
    x, y = _fp32_batch(1)
    _split_step(engine, chaos.poison_batch((x, y)))
    assert engine.overflow is False                 # fp32: no skip contract
    assert not np.isfinite(np.frombuffer(_master_bytes(engine),
                                         np.float32)).all()


def test_nan_sentinel_via_driver_chaos_point(tmpdir):
    """The driver-level injection: chaos nan_step poisons exactly one step
    and the run still reaches the target bitwise-finite.  fp32 on purpose:
    nan_skips counts only skips the SENTINEL caused — under fp16 the skip
    contract (and its skipped_steps accounting) pre-exists, and a dynamic
    scaler's calibration overflows must not read as NaN degradation."""
    factory = _engine_factory(NAN_CFG)
    dataset = _dataset()
    chaos.configure(nan_step=2)
    engine = resilience.run_resumable(
        factory, _split_step, steps=4, save_dir=str(tmpdir.join("ck")),
        data_loader=_loader(dataset))
    assert engine.global_steps == 4
    assert engine.skipped_steps == 1 and COUNTERS.nan_skips == 1
    assert np.isfinite(np.frombuffer(_master_bytes(engine),
                                     np.float32)).all()


# ------------------------------------------------------------ storage retry

def test_io_error_on_save_retries_then_succeeds(tmpdir):
    engine = _engine_factory(ZERO_CFG)()
    _split_step(engine, _fp32_batch(0))
    chaos.configure(io_fail_writes=2)
    save_dir = str(tmpdir.join("ck"))
    resilience.save_with_retry(engine, save_dir, tag="t0")   # io_retries=3
    assert COUNTERS.io_retries == 2
    assert validate_tag(save_dir, "t0")
    fresh = _engine_factory(ZERO_CFG)()
    path, _ = fresh.load_checkpoint(save_dir, tag="t0")
    assert path is not None


def test_io_retry_budget_exhausted_raises(tmpdir):
    engine = _engine_factory(ZERO_CFG)()
    chaos.configure(io_fail_writes=10)
    with pytest.raises(IOError, match="chaos: injected IO failure"):
        resilience.save_with_retry(engine, str(tmpdir.join("ck")), tag="t0",
                                   io_retries=2)
    assert COUNTERS.io_retries == 2


# ------------------------------------------------------------ hang watchdog

def test_watchdog_fires_and_names_stuck_frame():
    """An injected stall past the deadline produces a stack dump naming
    the stuck frame (chaos_stall) and the armed label, plus the recent
    step-timing history."""
    wd = Watchdog(timeout_s=0.3, abort=False, poll_s=0.05)
    with wd.armed("warmup step"):
        pass                                         # seeds the history
    with wd.armed("stalled collective"):
        chaos.chaos_stall(30.0, until=wd.fire_event)  # ends when it fires
    assert wd.fired
    assert COUNTERS.watchdog_fires == 1
    assert "chaos_stall" in wd.last_dump             # the stuck frame
    assert "stalled collective" in wd.last_dump      # the armed label
    assert "warmup step" in wd.last_dump             # timing history


def test_watchdog_near_miss_counter():
    wd = Watchdog(timeout_s=5.0, abort=False, near_miss_frac=0.02,
                  poll_s=0.05)
    with wd.armed("slowish step"):
        chaos.chaos_stall(0.2)
    assert not wd.fired
    assert COUNTERS.watchdog_near_misses == 1


def test_watchdog_abort_exit_code(tmpdir):
    """watchdog_abort: past the deadline the process dies with
    WATCHDOG_EXIT_CODE after flushing the dump — the launcher's restart
    contract."""
    script = tmpdir.join("stall.py")
    script.write(
        "from deepspeed_tpu.resilience import Watchdog, chaos\n"
        "wd = Watchdog(timeout_s=0.3, abort=True, poll_s=0.05)\n"
        "with wd.armed('stuck step'):\n"
        "    chaos.chaos_stall(60.0)\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", "")})
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == WATCHDOG_EXIT_CODE, (proc.returncode,
                                                   proc.stderr)
    assert "chaos_stall" in proc.stderr
    assert "stuck step" in proc.stderr


def test_engine_stall_injection_fires_watchdog(tmpdir):
    """The env/config-keyed stall lands INSIDE the engine's armed boundary
    region: the watchdog sees a hung collective and the dump names the
    stuck frame, the armed label AND — via the flight-recorder tail —
    the exact step the process stalled at, plus a loadable dump file
    (docs/observability.md "Flight recorder")."""
    from deepspeed_tpu.observability import flightrec

    cfg = dict(NAN_CFG)
    cfg["resilience"] = {"watchdog_timeout_s": 0.3}
    cfg["observability"] = {"flight_recorder_dir": str(tmpdir)}
    engine = _engine_factory(cfg)()
    engine._watchdog.poll_s = 0.05
    chaos.configure(stall_step=1, stall_s=1.5)
    _split_step(engine, _fp32_batch(0))      # boundary: global step 0 -> 1
    _split_step(engine, _fp32_batch(1))      # stalls at global step 1
    wd = engine._watchdog
    assert wd.fired
    assert "chaos_stall" in wd.last_dump
    assert "optimizer boundary step" in wd.last_dump
    # dump enrichment: the recorder tail names the stalled step (the last
    # armed entry is the boundary that never completed)
    assert "recent flight-recorder entries:" in wd.last_dump
    assert "arm label=boundary step=1" in wd.last_dump
    # ...and the ring was persisted as a loadable post-mortem artifact
    payload = flightrec.load_dump(
        str(tmpdir.join("flightrec_rank0_watchdog.json")))
    assert payload["reason"] == "watchdog"
    assert payload["entries"][-1]["kind"] == "arm"
    assert payload["entries"][-1]["step"] == 1
    assert COUNTERS.watchdog_fires >= 1


def test_engine_arms_watchdog_from_config():
    cfg = dict(NAN_CFG)
    cfg["resilience"] = {"watchdog_timeout_s": 120.0}
    engine = _engine_factory(cfg)()
    assert engine._watchdog is not None
    _split_step(engine, _fp32_batch(0))
    labels = [lbl for lbl, _ in engine._watchdog.timings]
    assert "backward (fused fwd+bwd)" in labels
    assert "optimizer boundary step" in labels


# --------------------------------------------- latest pointer + discovery

def test_corrupt_latest_falls_back_to_newest_valid_tag(tmpdir):
    """Regression (ISSUE 4 satellite): an empty/corrupt/stale `latest`
    pointer must fall back to the newest VALID tag dir, not break resume."""
    engine = _engine_factory(ZERO_CFG)()
    _split_step(engine, _fp32_batch(0))
    save_dir = str(tmpdir.join("ck"))
    engine.save_checkpoint(save_dir, tag="older")
    _split_step(engine, _fp32_batch(1))
    engine.save_checkpoint(save_dir, tag="newer")
    # deterministic mtime ordering regardless of filesystem timestamp
    # granularity
    for i, tag in enumerate(("older", "newer")):
        d = os.path.join(save_dir, tag)
        for f in os.listdir(d):
            os.utime(os.path.join(d, f), (1000 + i, 1000 + i))

    # (a) empty pointer
    with open(os.path.join(save_dir, "latest"), "w"):
        pass
    fresh = _engine_factory(ZERO_CFG)()
    path, _ = fresh.load_checkpoint(save_dir)
    assert path is not None and path.endswith("newer"), path

    # (b) pointer naming a deleted tag
    with open(os.path.join(save_dir, "latest"), "w") as f:
        f.write("gone_tag")
    fresh = _engine_factory(ZERO_CFG)()
    path, _ = fresh.load_checkpoint(save_dir)
    assert path is not None and path.endswith("newer"), path

    # (c) newest tag itself corrupt -> next-newest valid wins
    mfile = os.path.join(save_dir, "newer", "mp_rank_00_model_states.pt")
    with open(mfile, "wb") as f:
        f.write(b"DSTPUCK1garbage")
    assert not validate_tag(save_dir, "newer")
    assert find_latest_valid_tag(save_dir) == "older"

    # (d) nothing valid at all -> (None, None), not an exception
    import shutil
    shutil.rmtree(os.path.join(save_dir, "older"))
    fresh = _engine_factory(ZERO_CFG)()
    path, client = fresh.load_checkpoint(save_dir)
    assert path is None and client is None


def test_latest_pointer_written_atomically(tmpdir):
    """The pointer publish goes through temp + os.replace: after any save
    there is never a lingering temp file, and the pointer content is the
    full tag."""
    engine = _engine_factory(ZERO_CFG)()
    _split_step(engine, _fp32_batch(0))
    save_dir = str(tmpdir.join("ck"))
    engine.save_checkpoint(save_dir, tag="t0")
    assert not os.path.exists(os.path.join(save_dir, "latest.tmp"))
    with open(os.path.join(save_dir, "latest")) as f:
        assert f.read() == "t0"


# -------------------------------------------------------- launcher restarts

def _encode_world(info):
    from deepspeed_tpu.launcher.run import encode_world_info
    return encode_world_info(info)


RESTART_SCRIPT = """\
import os, sys
marker = os.environ["RESTART_MARKER"]
n = int(open(marker).read()) if os.path.exists(marker) else 0
open(marker, "w").write(str(n + 1))
sys.exit(0 if n + 1 >= int(os.environ["RESTART_SUCCEED_AT"]) else {code})
"""


def test_launcher_restarts_until_success(tmpdir, monkeypatch):
    """launch.py --max_restarts relaunches on the resilience exit codes
    and stops at the first clean exit."""
    from deepspeed_tpu.launcher import launch
    script = tmpdir.join("worker.py")
    script.write(RESTART_SCRIPT.format(code=RESUME_EXIT_CODE))
    marker = str(tmpdir.join("count"))
    monkeypatch.setenv("RESTART_MARKER", marker)
    monkeypatch.setenv("RESTART_SUCCEED_AT", "3")
    rc = launch.main([
        f"--world_info={_encode_world({'localhost': [0]})}",
        "--max_restarts=5", "--restart_backoff=0.01",
        str(script)])
    assert rc == 0
    assert open(marker).read() == "3"      # 1 launch + 2 restarts


def test_launcher_restart_budget_exhausted(tmpdir, monkeypatch):
    from deepspeed_tpu.launcher import launch
    script = tmpdir.join("worker.py")
    script.write(RESTART_SCRIPT.format(code=WATCHDOG_EXIT_CODE))
    marker = str(tmpdir.join("count"))
    monkeypatch.setenv("RESTART_MARKER", marker)
    monkeypatch.setenv("RESTART_SUCCEED_AT", "100")
    rc = launch.main([
        f"--world_info={_encode_world({'localhost': [0]})}",
        "--max_restarts=2", "--restart_backoff=0.01",
        str(script)])
    assert rc == WATCHDOG_EXIT_CODE
    assert open(marker).read() == "3"      # 1 launch + 2 restarts, then stop


def test_launcher_does_not_restart_real_crashes(tmpdir, monkeypatch):
    """A plain exit-1 crash would crash again: the budget must not be
    burned on it."""
    from deepspeed_tpu.launcher import launch
    script = tmpdir.join("worker.py")
    script.write(RESTART_SCRIPT.format(code=1))
    marker = str(tmpdir.join("count"))
    monkeypatch.setenv("RESTART_MARKER", marker)
    monkeypatch.setenv("RESTART_SUCCEED_AT", "100")
    rc = launch.main([
        f"--world_info={_encode_world({'localhost': [0]})}",
        "--max_restarts=5", "--restart_backoff=0.01",
        str(script)])
    assert rc == 1
    assert open(marker).read() == "1"      # no relaunch


def test_restart_delay_jittered_exponential():
    from deepspeed_tpu.launcher.launch import restart_delay_s
    lo = restart_delay_s(1, base=1.0, rand=lambda: 0.0)
    hi = restart_delay_s(1, base=1.0, rand=lambda: 1.0)
    assert lo == pytest.approx(0.5) and hi == pytest.approx(1.5)
    assert restart_delay_s(3, base=1.0, rand=lambda: 0.5) \
        == pytest.approx(4.0)
    assert restart_delay_s(30, base=1.0, cap=60.0, rand=lambda: 0.0) \
        == pytest.approx(30.0)             # capped before jitter


# ----------------------------------------------------------- observability

def test_counters_exported_through_engine():
    engine = _engine_factory(NAN_CFG)()
    _split_step(engine, _fp32_batch(0))
    got = engine.resilience_counters()
    assert set(got) == {"restarts", "preemptions", "nan_skips", "io_retries",
                        "watchdog_near_misses", "watchdog_fires",
                        "restore_seconds", "compile_cache_hits",
                        "compile_cache_misses"}

    class FakeWriter:
        def __init__(self):
            self.scalars = {}

        def add_scalar(self, name, value, step):
            self.scalars[name] = value

    engine.summary_writer = FakeWriter()
    x, y = _fp32_batch(1)
    _split_step(engine, chaos.poison_batch((x, y)))
    assert engine.summary_writer.scalars["Train/Resilience/nan_skips"] == 1
