"""Telemetry — the engine's single observability layer (docs/observability.md).

Four pieces, one facade:

* :mod:`~deepspeed_tpu.observability.spool` — MetricSpool: per-boundary
  loss/grad-norm/loss-scale/skip-flag accumulated in a device-side ring
  buffer inside the compiled step, drained by ONE batched host callback
  every ``report_window`` boundaries.  Replaces every per-step host fence
  (the ROADMAP-4 prerequisite); trajectory-neutral by construction.
* :mod:`~deepspeed_tpu.observability.tracing` — programmatic
  ``jax.profiler`` capture over a configured step window, ``dstpu/*``
  TraceAnnotation spans, and watchdog-triggered hang capture.
* :mod:`~deepspeed_tpu.observability.registry` — MetricRegistry exporter
  fan-out: engine throughput/goodput, resilience counters and
  compile-cache counters all emit through one path to TensorBoard and a
  schema-versioned JSONL event log (:mod:`~.schema`).
* goodput accounting — per-window measured step time, samples/s, optional
  MFU, and measured-vs-predicted capacity (the PR 6 planner handoff) with
  ``drift`` ratios, so prediction rot is a column, not a surprise.

Config::

    "observability": {
      "report_window": 0,          # >= 1 enables the spool
      "jsonl_path": null,          # JSONL event log (process 0)
      "trace_dir": null,           # or env DSTPU_TRACE_DIR (dst --trace_dir)
      "trace_start_step": 10,
      "trace_num_steps": 0,        # > 0 schedules a capture window
      "hang_capture": true,        # watchdog fire -> trace under trace_dir
      "hang_capture_s": 1.0,
      "planner_drift": true,       # predicted peak-HBM/boundary columns
      "flops_per_sample": null,    # enables the MFU column
      "peak_tflops_per_chip": null
    }
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from typing import Optional

import numpy as np

from deepspeed_tpu.observability import fences  # noqa: F401  (re-export)
from deepspeed_tpu.observability import schema  # noqa: F401
from deepspeed_tpu.observability import spool as spool_mod
from deepspeed_tpu.observability import tracing
from deepspeed_tpu.observability.registry import (JsonlSink, MetricRegistry,
                                                  TensorboardSink)
from deepspeed_tpu.observability.spool import MetricSpool
from deepspeed_tpu.observability.tracing import Tracer, annotate

logger = logging.getLogger(__name__)

__all__ = [
    "Telemetry", "MetricSpool", "MetricRegistry", "TensorboardSink",
    "JsonlSink", "Tracer", "annotate", "fences", "schema", "spool_mod",
    "tracing",
]


class Telemetry:
    """Per-engine telemetry driver.  Built by the engine at the end of
    ``__init__`` (after the summary writer and scheduler exist); holds the
    engine by weakref — the drain callback must never keep a dead engine
    alive."""

    def __init__(self, engine):
        import jax
        cfg = engine.config
        self._engine_ref = weakref.ref(engine)
        self.window = int(cfg.observability_report_window)
        self.registry = MetricRegistry()
        self._lock = threading.Lock()
        self._last_drain_ts = None      # set at first drain; window 1 is
        self._base_step = None          # unmeasured (it includes compile)
        self._skip_contract = bool(cfg.fp16_enabled
                                   or cfg.resilience_nan_sentinel)
        self._fp16 = bool(cfg.fp16_enabled)
        self._sentinel = bool(cfg.resilience_nan_sentinel)
        self._defer_overflow = None     # resolved lazily (needs scheduler)
        self._warned_sync_exception = False
        self.predictions = {}           # planner handoff (note_predictions)
        self._predictions_tried = False
        self.planner_drift = bool(cfg.observability_planner_drift)
        self.flops_per_sample = cfg.observability_flops_per_sample
        self.peak_tflops = cfg.observability_peak_tflops_per_chip
        self.measured_boundary_ms = None    # set by whoever measures it
        self.samples_per_step = (cfg.train_batch_size or 0)
        self._n_devices = jax.device_count()

        # sinks: TensorBoard rides the engine's writer, resolved LIVE at
        # emit time (rank-0 gated there; tests and users may swap the
        # writer after build); the JSONL event log writes on process 0
        self._tb = TensorboardSink(self._live_writer)
        self.registry.add_sink(self._tb)
        self.jsonl_path = None
        if (cfg.observability_jsonl_path
                and jax.process_index() == 0):
            self.jsonl_path = cfg.observability_jsonl_path
            self.registry.add_sink(JsonlSink(self.jsonl_path))

        # sources: the deduped scalar producers (legacy tag spellings kept:
        # Train/Samples/lr, Train/Resilience/*)
        from deepspeed_tpu.resilience import COUNTERS
        self.registry.register("resilience", COUNTERS.as_dict)
        self.registry.register("samples", self._samples_source)

        # spool (report_window >= 1)
        self.spool: Optional[MetricSpool] = None
        if self.window >= 1:
            self.spool = MetricSpool(self.window, self._on_window)
            # resolve the deferral decision NOW (the scheduler exists —
            # the engine builds Telemetry last): at report_window=1 the
            # first drain can run before any boundary bookkeeping, and a
            # lazily-unresolved flag would silently skip that window's
            # deferred skip accounting
            self.defers_overflow(engine)

        # tracer (trace_dir from config or DSTPU_TRACE_DIR)
        self.tracer: Optional[Tracer] = None
        trace_dir = tracing.resolve_trace_dir(cfg.observability_trace_dir)
        if trace_dir is not None:
            self.tracer = Tracer(
                trace_dir,
                start_step=cfg.observability_trace_start_step,
                num_steps=cfg.observability_trace_num_steps,
                hang_capture_s=cfg.observability_hang_capture_s)
        self.hang_capture = bool(cfg.observability_hang_capture)

    @classmethod
    def from_engine(cls, engine) -> "Telemetry":
        """Every engine gets a Telemetry: with no ``observability`` config
        the spool/tracer stay off, but the registry still owns ALL scalar
        export (the dedup of the three legacy TensorBoard write loops —
        one path whether metrics ride windows or boundaries)."""
        return cls(engine)

    # ------------------------------------------------------------- sources
    def _live_writer(self):
        engine = self._engine_ref()
        return engine.summary_writer if engine is not None else None

    def _samples_source(self) -> dict:
        engine = self._engine_ref()
        if engine is None:
            return {}
        return {"lr": float(engine.optimizer.param_groups[0]["lr"])}

    # --------------------------------------------------------------- spool
    @property
    def spool_active(self) -> bool:
        return self.spool is not None

    def defers_overflow(self, engine) -> bool:
        """Whether the engine may SKIP the per-boundary overflow host read
        (the last per-step fence).  True whenever the spool is on — except
        under the documented exception: fp16/nan-sentinel WITH an LR
        scheduler, whose skip-on-overflow contract (no scheduler step on a
        skipped boundary) needs the flag on the host before the next
        boundary's hyperparameter staging.  There the read stays and the
        spool still batches every other metric."""
        if self.spool is None:
            return False
        if self._defer_overflow is None:
            exception = (self._skip_contract
                         and engine.lr_scheduler is not None)
            self._defer_overflow = not exception
            if exception and not self._warned_sync_exception:
                self._warned_sync_exception = True
                logger.warning(
                    "telemetry: per-boundary overflow read RETAINED — the "
                    "%s skip contract must gate lr_scheduler.step() before "
                    "the next boundary (docs/observability.md \"The "
                    "scheduler exception\"); all other metrics still spool",
                    "fp16" if self._fp16 else "nan_sentinel")
        return self._defer_overflow

    def note_fused_plan(self, plan) -> None:
        """Adopt a capacity plan the engine's build-time gate already
        computed (engine._maybe_capacity_plan) — the drift columns must
        not re-trace the fused program to learn a number that exists."""
        if self.planner_drift and "predicted_peak_hbm_gb" not in \
                self.predictions:
            self.predictions["predicted_peak_hbm_gb"] = round(
                plan.peak_bytes / 2 ** 30, 6)
            if plan.profile is not None:
                self.predictions.setdefault("predicted_profile",
                                            plan.profile.name)

    def note_predictions(self, engine, batch) -> None:
        """One-time planner handoff (best-effort): predicted per-device
        peak HBM of the fused program (reused from the analysis gate's
        plan when it ran — see :meth:`note_fused_plan`) + predicted
        boundary wire time from the split-API plan, reported next to
        measurement in every window event (``*_drift`` columns)."""
        if self._predictions_tried or not self.planner_drift:
            return
        self._predictions_tried = True
        # defensive batch normalization: the engine hands the tuple form,
        # but a bare-array batch must not silently cost the drift columns
        batch = (tuple(batch) if isinstance(batch, (tuple, list))
                 else (batch,))
        try:
            if "predicted_peak_hbm_gb" not in self.predictions:
                fused = engine.plan_capacity(batch, train=True, fused=True)
                self.predictions["predicted_peak_hbm_gb"] = round(
                    fused.peak_bytes / 2 ** 30, 6)
            gas = engine.gradient_accumulation_steps()
            lead = next(iter(
                l.shape[0] for l in _tree_leaves(batch)))
            micro = tuple(a[:lead // gas] for a in batch)
            split = engine.plan_capacity(micro, train=True, fused=False)
            if split.boundary_comm is not None:
                self.predictions["predicted_boundary_ms"] = round(
                    split.boundary_comm.predicted_time_ms(), 6)
                if split.profile is not None:
                    self.predictions.setdefault("predicted_profile",
                                                split.profile.name)
        except Exception as e:  # pragma: no cover - defensive
            logger.warning("telemetry: capacity-plan handoff skipped: %s", e)

    def _on_window(self, rows: np.ndarray, pos: int) -> None:
        """Spool delivery (runtime callback thread on async drains, caller
        thread on flush): aggregate the window, settle the deferred
        skip bookkeeping, emit through the registry."""
        n = int(rows.shape[0])
        now = time.time()
        engine = self._engine_ref()
        with self._lock:
            base = self._base_step or 0
            last_ts, self._last_drain_ts = self._last_drain_ts, now
        step = base + pos

        skips = int(np.sum(rows[:, spool_mod.SKIP] > 0)) \
            if self._skip_contract else 0
        if engine is not None and self._defer_overflow:
            # deferred skip-on-overflow bookkeeping (the host read this
            # replaces): counters catch up at the drain, the device-side
            # skip (untouched master/moments) already happened in-program
            engine.skipped_steps += skips
            engine.overflow = bool(rows[-1, spool_mod.SKIP] > 0)
            if skips and self._sentinel and not self._fp16:
                from deepspeed_tpu.resilience import COUNTERS
                COUNTERS.nan_skips += skips
                logger.warning(
                    "resilience: %d non-finite-gradient boundar%s skipped "
                    "in the window ending at global step %d (nan_sentinel, "
                    "spooled)", skips, "y" if skips == 1 else "ies", step)

        event = {
            "step": int(step),
            "window_steps": n,
            "loss": float(rows[-1, spool_mod.LOSS]),
            "loss_mean": float(np.mean(rows[:, spool_mod.LOSS])),
            "grad_norm": float(rows[-1, spool_mod.GRAD_NORM]),
            "loss_scale": float(rows[-1, spool_mod.LOSS_SCALE]),
            "skipped": skips,
            "ts": now,
        }
        if last_ts is not None and now > last_ts:
            elapsed = now - last_ts
            event["step_ms"] = elapsed / n * 1000.0
            if self.samples_per_step:
                sps = n * self.samples_per_step / elapsed
                event["samples_per_sec"] = sps
                if self.flops_per_sample and self.peak_tflops:
                    event["mfu"] = (
                        (sps / self._n_devices)
                        * float(self.flops_per_sample)
                        / (float(self.peak_tflops) * 1e12))
        event.update(self._capacity_columns())
        sample_count = (getattr(engine, "sample_count", None)
                        if engine is not None else None)
        self.registry.emit(event, sample_count=sample_count)

    def _capacity_columns(self) -> dict:
        """Measured-vs-predicted capacity (PR 6 planner handoff)."""
        out = dict(self.predictions)
        measured = _measured_peak_hbm_gb()
        if measured is not None:
            out["measured_peak_hbm_gb"] = round(measured, 4)
            pred = out.get("predicted_peak_hbm_gb")
            if pred:
                out["hbm_drift"] = round(measured / pred, 4)
        if self.measured_boundary_ms is not None:
            out["measured_boundary_ms"] = round(self.measured_boundary_ms, 4)
            pred = out.get("predicted_boundary_ms")
            if pred:
                out["boundary_drift"] = round(
                    self.measured_boundary_ms / pred, 4)
        return out

    # --------------------------------------------------- engine-facing hooks
    def note_spool_base_step(self, global_steps: int) -> None:
        """Anchor ring positions to engine global steps (set at the first
        spooled boundary; a resumed engine anchors at its restored step)."""
        with self._lock:
            if self._base_step is None:
                self._base_step = int(global_steps)

    def rebase_steps(self, global_steps: int) -> None:
        """Re-anchor window step numbering after a checkpoint restore:
        subsequent events report ``restored step + appends since``."""
        if self.spool is None:
            return
        with self._lock:
            self._base_step = int(global_steps) - self.spool._appended

    def emit_boundary_scalars(self, sample_count) -> None:
        """Legacy-cadence TensorBoard export (spool OFF): the same source
        snapshot the window path emits, written per boundary through the
        ONE TensorBoard sink — the dedup of the three historical write
        loops, and one owner of the tag spelling (a counters-only event
        writes no ``Train/Telemetry/*`` window scalars)."""
        self._tb.emit({"step": sample_count,
                       "counters": self.registry.counters_snapshot()},
                      sample_count=sample_count)

    def maybe_trace(self, global_steps: int) -> None:
        if self.tracer is not None:
            self.tracer.maybe_window(global_steps)

    def hang_capture_hook(self):
        """The watchdog ``on_fire`` callable (None when tracing is off)."""
        if self.tracer is None or not self.hang_capture:
            return None
        return lambda: self.tracer.capture_hang()

    def flush(self) -> None:
        """Drain the final (possibly partial) window synchronously — run
        end and preemption drain; the ONE deliberate telemetry fence."""
        if self.spool is not None:
            self.spool.flush()

    def close(self) -> None:
        self.flush()
        if self.tracer is not None:
            self.tracer.stop()
        self.registry.close()


def _tree_leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


def _measured_peak_hbm_gb() -> Optional[float]:
    """Per-device peak HBM from the PJRT allocator (None on backends
    without memory stats — CPU)."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:  # pragma: no cover - defensive
        return None
    peak = stats.get("peak_bytes_in_use")
    return None if peak is None else peak / 2 ** 30
