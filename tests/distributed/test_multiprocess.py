"""Multi-process distributed tier (VERDICT r2 missing #1).

Every test here spawns REAL processes that rendezvous through
``jax.distributed.initialize`` — the launcher env contract, the
``addressable_shards`` checkpoint ownership logic, and the pre-``latest``
barrier execute with ``process_count > 1`` for the first time anywhere in
the suite.  Reference analog: ``@distributed_test``
(/root/reference/tests/unit/common.py:14-100) and the checkpoint suite built
on it.
"""

import os
import subprocess
import sys
import textwrap

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

from harness import REPO, free_port, spawn_distributed, worker_env  # noqa: E402

pytestmark = pytest.mark.distributed


@pytest.mark.parametrize("world_size", [2, 3])
def test_rendezvous_and_psum(world_size, tmpdir):
    spawn_distributed("psum_closed_form", world_size=world_size,
                      local_devices=2,
                      env_extra={"DSTPU_TEST_DIR": str(tmpdir)})


def test_zero_checkpoint_resume_multiprocess(tmpdir):
    spawn_distributed("zero_ckpt_resume", world_size=2, local_devices=2,
                      env_extra={"DSTPU_TEST_DIR": str(tmpdir)})


def test_zero_pps_checkpoint_resume_multiprocess(tmpdir):
    """parameter_parallel_size sub-groups across real processes: partition
    dedup on save + resume parity (tests/test_zero_pps.py single-process
    twin)."""
    spawn_distributed("zero_pps_ckpt_resume", world_size=2, local_devices=2,
                      env_extra={"DSTPU_TEST_DIR": str(tmpdir)})


def test_zero_pps_mp_checkpoint_resume_multiprocess(tmpdir):
    """pps=2 x mp=2 x dp=4 across real processes (VERDICT r3 item 9): the
    block-tiled [S, local] rows save only distinct partitions and resume
    bit-exact."""
    spawn_distributed("zero_pps_mp_ckpt_resume", world_size=2,
                      local_devices=4,
                      env_extra={"DSTPU_TEST_DIR": str(tmpdir)})


def test_zero_mp_checkpoint_roles_multiprocess(tmpdir):
    spawn_distributed("zero_mp_ckpt_roles", world_size=2, local_devices=2,
                      env_extra={"DSTPU_TEST_DIR": str(tmpdir)})


# --------------------------------------------------------------- launcher E2E

E2E_SCRIPT = textwrap.dedent("""\
    import argparse, os, sys
    sys.path.insert(0, {repo!r})
    from deepspeed_tpu.parallel.topology import init_distributed
    init_distributed()          # launcher exported DSTPU_* for this process

    import jax
    import jax.numpy as jnp
    import numpy as np
    import deepspeed_tpu as ds

    class TinyModel:
        def init_params(self, rng):
            return {{"w": jnp.ones((8, 8), jnp.float32) * 0.1,
                     "b": jnp.zeros((8,), jnp.float32)}}
        def apply(self, params, x, y):
            logits = x @ params["w"].astype(x.dtype) + params["b"].astype(x.dtype)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            onehot = jax.nn.one_hot(y, 8, dtype=jnp.float32)
            return -jnp.mean(jnp.sum(onehot * logp, -1))

    parser = argparse.ArgumentParser()
    parser.add_argument("--local_rank", type=int, default=-1)
    parser = ds.add_config_arguments(parser)
    args = parser.parse_args()
    assert args.deepspeed, "--deepspeed flag did not reach the script"
    assert jax.process_count() == 2, jax.process_count()

    engine, _, _, _ = ds.initialize(args=args, model=TinyModel())
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 8)).astype(np.float16)
    y = rng.integers(0, 8, size=(8,)).astype(np.int32)
    for _ in range(2):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    engine.save_checkpoint(os.environ["DSTPU_E2E_CKPT"], tag="e2e")
    print(f"E2E_OK rank={{jax.process_index()}} loss={{float(loss):.6f}}",
          flush=True)
""")


def test_dst_local_launcher_end_to_end(tmpdir):
    """`dst --launcher local` → launcher/launch.py → spawned training
    processes → env-contract rendezvous → ZeRO train + multi-host checkpoint.
    Fails if the DSTPU_* env names, the rank mapping, or the checkpoint
    roles break (VERDICT r2 weak #5)."""
    script = tmpdir.join("train_e2e.py")
    script.write(E2E_SCRIPT.format(repo=REPO))
    cfg = tmpdir.join("ds_config.json")
    cfg.write("""{
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
        "fp16": {"enabled": true, "loss_scale": 64.0},
        "zero_optimization": true
    }""")
    ckdir = tmpdir.mkdir("ckpt")
    port = free_port()

    env = worker_env(pid=0, world_size=1, port=port, local_devices=2,
                     extra={"DSTPU_E2E_CKPT": str(ckdir)})
    # the repo isn't pip-installed in the test environment; `dst` (and the
    # launcher module it spawns) must still resolve deepspeed_tpu
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # dst itself must not pre-claim a rank — the launcher assigns them
    for var in ("DSTPU_COORDINATOR", "DSTPU_NUM_PROCESSES",
                "DSTPU_PROCESS_ID"):
        env.pop(var, None)

    cmd = [sys.executable, os.path.join(REPO, "bin", "dst"),
           "--launcher", "local", "--num_chips", "2",
           f"--master_port={port}",
           str(script), "--deepspeed", f"--deepspeed_config={cfg}"]
    proc = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=420)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"dst exited {proc.returncode}:\n{out}"
    for rank in (0, 1):
        assert f"E2E_OK rank={rank}" in out, \
            f"rank {rank} sentinel missing:\n{out}"
    # both processes trained the same global program — identical losses
    losses = sorted(set(line.split("loss=")[1] for line in out.splitlines()
                        if "E2E_OK" in line))
    assert len(losses) == 1, f"ranks diverged: {losses}\n{out}"
    files = sorted(os.listdir(os.path.join(str(ckdir), "e2e")))
    assert "mp_rank_00_model_states.pt" in files, files
    zero_shards = [f for f in files if f.startswith("zero_pp_rank_")]
    assert len(zero_shards) == 4, files  # one per DP partition (2 procs x 2)
    with open(os.path.join(str(ckdir), "latest")) as f:
        assert f.read().strip() == "e2e"
