"""Fleet observability tests (docs/observability.md "Fleet view").

Contracts pinned here:

* trajectory neutrality — fleet aggregation on vs observability off is
  bitwise identical (losses + master weights), and adds zero per-step
  fences (the fleet path consumes numbers the drain already put on the
  host);
* fleet events — one ``dstpu.telemetry.fleet`` line per window on rank 0,
  schema-valid, with per-host spreads, counter roll-ups and straggler /
  anomaly flags;
* detectors — a stalled host is flagged by host-side time (leave-one-out
  median), spikes by rolling baselines, starvation by data-wait fraction;
  a steady run flags NOTHING (the no-false-positive regression);
* startup events — cold start is a recorded number;
* flight recorder — bounded ring, loadable dumps, watchdog enrichment;
* health endpoints — /healthz, /status, /metrics answer from a live
  engine; /metrics parses as Prometheus text;
* validator CLI — mixed window/fleet/startup streams validate; invalid
  and empty streams still exit 2 (the pinned gate).

The 2-process straggler/flight-recorder legs live in
``tests/distributed/test_multiprocess.py`` (``fleet_straggler_watchdog``).
"""

import json
import os
import urllib.request

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.observability import (detectors, fences, flightrec,
                                         health_mod, schema)
from deepspeed_tpu.observability import __main__ as obs_cli
from deepspeed_tpu.resilience import COUNTERS, chaos
from simple_model import SimpleModel

HIDDEN = 8


@pytest.fixture(autouse=True)
def _reset_state():
    COUNTERS.reset()
    detectors.COUNTERS.reset()
    chaos.reset()
    yield
    COUNTERS.reset()
    detectors.COUNTERS.reset()
    chaos.reset()


def _cfg(obs=None, extra=None):
    cfg = {
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "steps_per_print": 10 ** 9,
    }
    if obs is not None:
        cfg["observability"] = obs
    if extra:
        cfg.update(extra)
    return cfg


def _engine(cfg):
    model = SimpleModel(hidden_dim=HIDDEN)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    return engine


def _batch(i, n=16):
    rng = np.random.default_rng(i)
    x = rng.normal(size=(n, HIDDEN)).astype(np.float32)
    y = rng.integers(0, HIDDEN, size=(n,)).astype(np.int32)
    return x, y


def _master_bytes(engine):
    return b"".join(np.asarray(jax.device_get(l)).tobytes()
                    for l in jax.tree_util.tree_leaves(engine.master))


# ------------------------------------------------------- fleet event stream

def test_fleet_events_emitted_and_schema_valid(tmpdir):
    """Single-process fleet-of-1: every window produces a fleet event
    (loopback transport — same aggregation code path as multi-host),
    interleaved with window events in one schema-valid stream."""
    jsonl = str(tmpdir.join("t.jsonl"))
    e = _engine(_cfg(obs={"report_window": 2, "jsonl_path": jsonl,
                          "fleet": True, "fleet_wait_s": 10.0}))
    for i in range(5):
        e.train_batch(_batch(i))
    e.flush_telemetry()
    assert schema.validate_jsonl(jsonl) == []
    lines = [json.loads(l) for l in open(jsonl)]
    fl = [ev for ev in lines if ev["schema"] == schema.FLEET_SCHEMA_ID]
    win = [ev for ev in lines if ev["schema"] == schema.SCHEMA_ID]
    # one fleet event per drained window (2 full + the flushed partial)
    assert [ev["window"] for ev in fl] == [1, 2, 3]
    assert len(win) == 3
    for ev in fl:
        assert ev["n_hosts"] == 1
        assert ev["reported_hosts"] == 1
        assert ev["missing_hosts"] == []
        assert ev["stragglers"] == []
        assert "0" in ev["per_host"]
        assert ev["per_host"]["0"]["step"] == ev["step"]
        # counter roll-up carries the summed resilience counters
        assert "resilience/nan_skips" in ev["counters"]
    # measured windows roll host time up into the spread columns
    assert fl[1]["host_ms_median"] is not None
    assert fl[1]["samples_per_sec_sum"] > 0
    assert detectors.COUNTERS.fleet_windows == 3
    assert detectors.COUNTERS.fleet_reports_missing == 0


def test_fleet_bitwise_on_off_and_zero_fences():
    """THE neutrality contract with the full fleet layer on: bitwise
    identical losses + master weights vs observability off, and zero
    per-step fences (one deliberate flush at the end)."""
    e_off = _engine(_cfg())
    e_on = _engine(_cfg(obs={"report_window": 2, "fleet": True}))
    l_off, l_on = [], []
    for i in range(5):
        l_off.append(float(e_off.train_batch(_batch(i))))
        l_on.append(float(e_on.train_batch(_batch(i))))
    before = fences.FENCE_COUNT
    for i in range(5, 9):
        e_on.train_batch(_batch(i))
    assert fences.FENCE_COUNT == before, \
        "fleet aggregation took a per-step host fence"
    e_on.flush_telemetry()
    assert fences.FENCE_COUNT == before + 1     # the one flush
    for i in range(5, 9):
        e_off.train_batch(_batch(i))
    assert l_off == l_on
    assert _master_bytes(e_off) == _master_bytes(e_on)


def test_steady_run_no_false_positives(tmpdir):
    """The anomaly/straggler detectors flag NOTHING on a steady run —
    alarm fatigue is how observability gets turned off."""
    jsonl = str(tmpdir.join("t.jsonl"))
    e = _engine(_cfg(obs={"report_window": 2, "jsonl_path": jsonl,
                          "fleet": True}))
    for i in range(16):     # 8 windows: plenty of baseline history
        e.train_batch(_batch(i))
    e.flush_telemetry()
    lines = [json.loads(l) for l in open(jsonl)]
    for ev in lines:
        if ev["schema"] == schema.SCHEMA_ID:
            assert ev["anomalies"] == [], ev
        elif ev["schema"] == schema.FLEET_SCHEMA_ID:
            assert ev["stragglers"] == [], ev
            assert ev["anomalies"] == [], ev
    assert detectors.COUNTERS.stragglers_flagged == 0
    assert detectors.COUNTERS.loss_spikes == 0
    assert detectors.COUNTERS.grad_norm_spikes == 0
    assert detectors.COUNTERS.data_starvation_windows == 0


def test_loss_spike_flagged_in_window_and_fleet(tmpdir):
    """A poisoned batch mid-run spikes the window loss: the per-host
    detector flags it, the flag rides the window event, the fleet event
    and the counters."""
    jsonl = str(tmpdir.join("t.jsonl"))
    e = _engine(_cfg(obs={"report_window": 1, "jsonl_path": jsonl,
                          "fleet": True, "spike_factor": 4.0}))
    for i in range(8):
        x, y = _batch(i)
        if i == 6:          # after >= 3 baseline windows
            x = (x * 1000.0).astype(np.float32)
        e.train_batch((x, y))
    e.flush_telemetry()
    lines = [json.loads(l) for l in open(jsonl)]
    spiked = [ev for ev in lines if ev["schema"] == schema.SCHEMA_ID
              and "loss_spike" in (ev["anomalies"] or [])]
    assert [ev["step"] for ev in spiked] == [7]
    fleet_flags = [ev for ev in lines
                   if ev["schema"] == schema.FLEET_SCHEMA_ID
                   and {"rank": 0, "kind": "loss_spike"} in ev["anomalies"]]
    assert len(fleet_flags) == 1
    assert detectors.COUNTERS.loss_spikes >= 1


# ------------------------------------------------------------------ detectors

def test_straggler_detector_leave_one_out():
    det = detectors.StragglerDetector(2.0)
    healthy = {r: {"host_ms": 2.0 + 0.1 * r, "step": 10}
               for r in range(4)}
    v = det.check_fleet(healthy)
    assert v["stragglers"] == []
    slow = dict(healthy)
    slow[2] = {"host_ms": 900.0, "step": 10}
    v = det.check_fleet(slow)
    assert v["stragglers"] == [2]
    assert v["straggler_index"] > 100
    assert detectors.COUNTERS.stragglers_flagged == 1
    # sub-floor deviations are jitter, not stragglers
    jitter = {0: {"host_ms": 1.0}, 1: {"host_ms": 40.0}}
    assert det.check_fleet(jitter)["stragglers"] == []


def test_straggler_detector_data_wait_counts():
    """Data wait is part of the host-side signal: a starving host is a
    straggler even when its pre-dispatch compute time is fine."""
    det = detectors.StragglerDetector(2.0)
    v = det.check_fleet({
        0: {"host_ms": 2.0, "data_wait_ms": 0.0},
        1: {"host_ms": 2.0, "data_wait_ms": 800.0},
    })
    assert v["stragglers"] == [1]


def test_spike_detector_rejects_learning_baseline():
    """A spiking value must NOT join the baseline — otherwise a diverging
    run teaches the detector that divergence is normal."""
    sd = detectors.SpikeDetector(3.0)
    for v in (1.0, 1.1, 0.9, 1.0):
        assert not sd.check(v)
    assert sd.check(100.0)
    assert sd.check(100.0)      # still a spike on repeat
    assert not sd.check(1.05)   # baseline intact
    assert sd.check(float("nan"))   # non-finite is always a spike


def test_window_anomaly_detector_starvation():
    det = detectors.WindowAnomalyDetector(rank=0, spike_factor=5.0,
                                          starvation_frac=0.5)
    ok = {"loss_mean": 1.0, "grad_norm": 1.0, "step_ms": 100.0,
          "data_wait_ms": 10.0, "step": 1}
    assert det.check_window(ok) == []
    starved = dict(ok, data_wait_ms=90.0, step=2)
    assert "data_starvation" in det.check_window(starved)
    assert detectors.COUNTERS.data_starvation_windows == 1


# ------------------------------------------------------------ flight recorder

def test_flight_recorder_ring_bounds_and_dump(tmpdir):
    r = flightrec.FlightRecorder(capacity=8, rank=3)
    for i in range(20):
        r.record("boundary", step=i)
    entries = r.tail()
    assert len(entries) == 8
    assert [e["step"] for e in entries] == list(range(12, 20))
    assert "boundary step=19" in r.format_tail(4)
    path = r.dump("test", path=str(tmpdir.join("d.json")))
    payload = flightrec.load_dump(path)
    assert payload["rank"] == 3
    assert len(payload["entries"]) == 8
    # per-reason idempotence: a second dump returns the first artifact
    assert r.dump("test", path=str(tmpdir.join("other.json"))) == path
    with pytest.raises(ValueError, match="not a flight-recorder dump"):
        bad = tmpdir.join("bad.json")
        bad.write('{"schema": "something.else"}')
        flightrec.load_dump(str(bad))


def test_flight_recorder_records_engine_breadcrumbs(tmpdir):
    """A trained engine leaves the post-mortem trail: arm + boundary per
    step, window drains, checkpoint saves."""
    e = _engine(_cfg(obs={"report_window": 2,
                          "flight_recorder_dir": str(tmpdir)}))
    for i in range(3):
        e.train_batch(_batch(i))
    e.save_checkpoint(str(tmpdir.join("ck")), tag="t0")
    e.flush_telemetry()
    kinds = [en["kind"] for en in flightrec.RECORDER.tail()]
    assert "arm" in kinds and "boundary" in kinds
    assert "window" in kinds and "checkpoint.save" in kinds
    steps = [en["step"] for en in flightrec.RECORDER.tail()
             if en["kind"] == "boundary"]
    assert steps[-1] == 3


def test_flight_recorder_disabled_by_config():
    e = _engine(_cfg(obs={"flight_recorder": 0}))
    flightrec.RECORDER.record("x")
    assert flightrec.RECORDER.tail() == []
    assert flightrec.RECORDER.dump("test") is None
    del e


# ------------------------------------------------------------ health endpoints

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read()


def test_health_endpoints_live_engine(tmpdir):
    """/healthz, /status and /metrics answer from a live engine; /metrics
    parses as Prometheus text and carries the window goodput."""
    e = _engine(_cfg(obs={"report_window": 2, "fleet": True}))
    srv = health_mod.HealthServer(0, e.telemetry, rank=0)
    try:
        for i in range(4):
            e.train_batch(_batch(i))
        e.flush_telemetry()
        base = f"http://127.0.0.1:{srv.port}"
        code, body = _get(base + "/healthz")
        assert code == 200 and json.loads(body)["ok"] is True
        code, body = _get(base + "/status")
        status = json.loads(body)
        assert status["step"] == 4
        assert status["last_window"]["window_steps"] == 2
        assert status["last_fleet"]["n_hosts"] == 1
        assert "resilience/nan_skips" in status["counters"]
        code, body = _get(base + "/metrics")
        metrics = health_mod.parse_prometheus_text(body.decode())
        assert metrics["dstpu_step"] == 4
        assert metrics["dstpu_window_samples_per_sec"] > 0
        assert metrics["dstpu_fleet_reported_hosts"] == 1
        assert metrics["dstpu_healthy"] == 1
        code, _ = _get(base + "/nope")
        assert code == 404
    except urllib.error.HTTPError as err:
        if err.code != 404:
            raise
    finally:
        srv.close()


def test_healthz_degrades_on_watchdog_fire():
    """A fired watchdog flips /healthz to 503: alive but wedged is the
    state an orchestrator must replace."""
    e = _engine(_cfg(obs={"report_window": 2}))
    srv = health_mod.HealthServer(0, e.telemetry, rank=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        assert _get(base + "/healthz")[0] == 200
        COUNTERS.watchdog_fires += 1
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base + "/healthz")
        assert exc.value.code == 503
    finally:
        srv.close()


def test_resolve_health_port_env_and_offset(monkeypatch):
    from deepspeed_tpu.observability.health import (ENV_HEALTH_PORT,
                                                    resolve_health_port)
    monkeypatch.delenv(ENV_HEALTH_PORT, raising=False)
    assert resolve_health_port(0) is None
    assert resolve_health_port(8090) == 8090 + jax.process_index()
    monkeypatch.setenv(ENV_HEALTH_PORT, "9100")
    assert resolve_health_port(0) == 9100 + jax.process_index()
    # config beats env
    assert resolve_health_port(8090) == 8090 + jax.process_index()
    monkeypatch.setenv(ENV_HEALTH_PORT, "junk")
    assert resolve_health_port(0) is None


def test_prometheus_text_round_trip():
    # small/negative/non-finite values render via %g — the parser must
    # accept every rendering the emitter produces (1e-05 once failed a
    # hand-rolled char class)
    text = health_mod.prometheus_text(
        {"a/b": 1.5, "skip_none": None, "bool_skipped": True, "c": 2,
         "tiny": 1e-05, "neg": -2.5, "inf": float("inf")},
        labels={"rank": 1})
    parsed = health_mod.parse_prometheus_text(text)
    assert parsed["dstpu_a_b"] == 1.5 and parsed["dstpu_c"] == 2.0
    assert parsed["dstpu_tiny"] == 1e-05
    assert parsed["dstpu_neg"] == -2.5
    assert parsed["dstpu_inf"] == float("inf")
    with pytest.raises(ValueError, match="malformed"):
        health_mod.parse_prometheus_text("not a metric line at all")
    with pytest.raises(ValueError, match="malformed"):
        health_mod.parse_prometheus_text("dstpu_x{rank=\"0\"} junkvalue")


# ------------------------------------------------------------- schema v2 / CLI

def _valid_fleet_event():
    ev = {"schema": schema.FLEET_SCHEMA_ID, "version": 2, "ts": 1.0,
          "window": 1, "step": 4, "n_hosts": 2, "reported_hosts": 2,
          "missing_hosts": [], "stragglers": [1],
          "anomalies": [{"rank": 1, "kind": "loss_spike"}],
          "skipped_total": 0, "counters": {"resilience/nan_skips": 0},
          "per_host": {"0": {}, "1": {}}}
    for name in schema.FLEET_FIELDS:
        ev.setdefault(name, None)
    return ev


def test_fleet_event_schema_validation():
    ev = _valid_fleet_event()
    assert schema.validate_fleet_event(ev) is None
    assert schema.validate_any(ev) is None
    assert "reported_hosts" in schema.validate_fleet_event(
        {**ev, "reported_hosts": 3})
    assert "stragglers" in schema.validate_fleet_event(
        {**ev, "stragglers": ["one"]})
    assert "anomalies" in schema.validate_fleet_event(
        {**ev, "anomalies": ["loss_spike"]})
    assert "version" in schema.validate_fleet_event({**ev, "version": 1})


def test_window_schema_v1_still_accepted():
    """PR 7 logs (version 1, no v2 fields) must keep validating — the
    fleet columns are additive."""
    v1 = {"schema": schema.SCHEMA_ID, "version": 1, "ts": 1.0, "step": 3,
          "window_steps": 3, "skipped": 0, "counters": {}}
    for name, spec in schema.FIELDS.items():
        if len(spec) < 3:       # v1 fields only
            v1.setdefault(name, None)
    assert schema.validate_event(v1) is None
    # ...but a v2 event MISSING the fleet columns is invalid
    v2 = dict(v1, version=2)
    assert "missing field" in schema.validate_event(v2)


def test_validator_cli_mixed_stream_and_exit_codes(tmpdir, capsys):
    """The validator accepts mixed window/fleet/startup streams and still
    exits 2 on invalid or empty files — the pinned CI gate."""
    mixed = str(tmpdir.join("mixed.jsonl"))
    e = _engine(_cfg(obs={"report_window": 2, "jsonl_path": mixed,
                          "fleet": True}))
    for i in range(4):
        e.train_batch(_batch(i))
    e.flush_telemetry()
    assert obs_cli.main([mixed]) == 0
    out = capsys.readouterr().out
    # the summary names every schema present in the stream
    assert "window" in out and "fleet" in out and "startup" in out

    unknown = str(tmpdir.join("unknown.jsonl"))
    with open(unknown, "w") as f:
        f.write(json.dumps({"schema": "dstpu.telemetry.nonsense",
                            "version": 9}) + "\n")
    assert obs_cli.main([unknown]) == 2
    err = capsys.readouterr().err
    assert "unknown schema" in err

    empty = str(tmpdir.join("empty.jsonl"))
    open(empty, "w").close()
    assert obs_cli.main([empty]) == 2

    # a stream mixing valid and invalid lines fails as a whole
    half = str(tmpdir.join("half.jsonl"))
    with open(half, "w") as f:
        with open(mixed) as src:
            f.write(src.readline())
        f.write("not json\n")
    assert obs_cli.main([half]) == 2


# --------------------------------------------------------------- config guards

def test_fleet_config_validation():
    with pytest.raises(DeepSpeedConfigError, match="fleet"):
        _engine(_cfg(obs={"fleet": True}))      # needs report_window
    with pytest.raises(DeepSpeedConfigError, match="straggler_factor"):
        _engine(_cfg(obs={"report_window": 2, "straggler_factor": 1.0}))
    with pytest.raises(DeepSpeedConfigError, match="health_port"):
        _engine(_cfg(obs={"health_port": 99999}))
    with pytest.raises(DeepSpeedConfigError, match="starvation_frac"):
        _engine(_cfg(obs={"report_window": 2, "starvation_frac": 0.0}))
    with pytest.raises(DeepSpeedConfigError, match="flight_recorder"):
        _engine(_cfg(obs={"flight_recorder": -1}))
    with pytest.raises(DeepSpeedConfigError, match="fleet_wait_s"):
        _engine(_cfg(obs={"report_window": 2, "fleet": True,
                          "fleet_wait_s": 0}))
    with pytest.raises(DeepSpeedConfigError, match="unknown observability"):
        _engine(_cfg(obs={"flet": True}))


def test_launcher_health_port_flag():
    from deepspeed_tpu.launcher import launch, run
    args = run.parse_args(["--health_port", "8090", "script.py"])
    assert args.health_port == 8090
    largs = launch.parse_args(["--world_info", run.encode_world_info(
        {"localhost": [0]}), "--health_port", "8090", "x.py"])
    assert largs.health_port == 8090
