# Shared config loading for the TPU-VM fleet scripts (the azure/ analog of
# the reference; GCP TPU VMs instead of Azure GPU VMs).  Requires gcloud + jq.
set -euo pipefail

CONFIG_FILE=${CONFIG_FILE:-"$(dirname "$0")/tpu_config.json"}
if [ ! -f "${CONFIG_FILE}" ]; then
    echo "Cannot find ${CONFIG_FILE}" >&2
    exit 1
fi
command -v jq >/dev/null || { echo "jq is required" >&2; exit 1; }
command -v gcloud >/dev/null || { echo "gcloud is required" >&2; exit 1; }

cfg() {
    local v
    v=$(jq -er "$1 // empty" "${CONFIG_FILE}") && [ -n "${v}" ] || {
        echo "missing/empty key $1 in ${CONFIG_FILE}" >&2
        exit 1
    }
    echo "${v}"
}

PROJECT=$(cfg .project)
ZONE=$(cfg .zone)
TPU_NAME=$(cfg .tpu_name)
ACCEL=$(cfg .accelerator_type)
RUNTIME=$(cfg .runtime_version)

GC="gcloud compute tpus tpu-vm"
GFLAGS=(--project "${PROJECT}" --zone "${ZONE}")
