"""ZeRO stage 2: gradient partitioning (beyond the reference's v0.1.0).

Each micro-step's gradients reduce-scatter onto the owned flat partition
INSIDE the accumulation loop, so the grad-accumulation buffer shrinks
from full model size to ``1/pps``.  Linearity makes per-micro
scatter-then-accumulate equal the stage-1 accumulate-then-scatter, so
stage 2 must reproduce stage-1 trajectories exactly (same collectives,
reordered) — pinned here along with the memory claim and composition
with MP / parameter-parallel sub-groups / checkpointing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.models import GPT2
from deepspeed_tpu.parallel.topology import make_mesh

pytestmark = pytest.mark.slow

VOCAB, SEQ = 64, 16


def tiny_gpt2():
    return GPT2.from_size("tiny", vocab_size=VOCAB, max_seq_len=SEQ,
                          num_layers=2, hidden_size=32, num_heads=4)


def lm_batch(batch, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, VOCAB, size=(batch, SEQ)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    labels[:, -1] = -1
    return toks, labels


def make_engine(stage, mp=1, gas=1, pps=None, **cfg_over):
    zero = {"stage": stage}
    if pps:
        zero["parameter_parallel_size"] = pps
    cfg = {
        "train_batch_size": 8 * gas,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 10 ** 6,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": zero,
        "fp16": {"enabled": True, "initial_scale_power": 8},
    }
    cfg.update(cfg_over)
    model = tiny_gpt2()
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(7)),
        mesh=make_mesh(model_parallel_size=mp))
    return engine


def run_fused(engine, steps=4, gas=1):
    return [float(engine.train_batch(lm_batch(8 * gas, seed=i)))
            for i in range(steps)]


@pytest.mark.parametrize("gas", [1, 2])
def test_stage2_matches_stage1_fused(gas):
    """Fused train_batch: stage-2 trajectory == stage-1 (the per-micro
    scatter must commute with accumulation)."""
    ref = run_fused(make_engine(1, gas=gas), gas=gas)
    e2 = make_engine(2, gas=gas)
    assert e2.zero_stage == 2
    got = run_fused(e2, gas=gas)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)


def test_stage2_matches_stage1_split_api():
    """Split API: backward() accumulates the flat PARTITION, step()
    consumes it — trajectory parity with stage 1."""
    acc_shapes = {}

    def run_split(stage):
        engine = make_engine(stage)
        out = []
        for i in range(4):
            loss = engine(*lm_batch(8, seed=i))
            engine.backward(loss)
            acc_shapes[stage] = jax.tree_util.tree_map(
                lambda a: a.shape, engine._acc)
            engine.step()
            out.append(float(loss))
        return out, engine

    ref, _ = run_split(1)
    got, e2 = run_split(2)
    # the stage-2 accumulator really is the flat partition, not a tree
    assert acc_shapes[2] == (e2.flat_meta.padded,), acc_shapes[2]
    assert len(jax.tree_util.tree_leaves(acc_shapes[1])) > 1
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)


def test_stage2_with_mp_and_pps():
    """Stage 2 composes with tensor parallelism and parameter-parallel
    sub-groups (the [S, local] rows scatter per micro like the 1-D
    layout)."""
    ref = run_fused(make_engine(1, mp=2))
    got = run_fused(make_engine(2, mp=2))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)

    ref = run_fused(make_engine(1, pps=2))
    got = run_fused(make_engine(2, pps=2))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)


def test_stage2_with_pipeline():
    """Stage 2 under pp=2: the per-(stage, shard) [1, part] rows scatter
    per micro and match the stage-1 trajectory."""
    from deepspeed_tpu.models import GPT2Pipelined

    def run(stage):
        model = GPT2Pipelined.from_size(
            "tiny", vocab_size=VOCAB, max_seq_len=SEQ, num_layers=2,
            hidden_size=32, num_heads=4, num_micro_batches=2)
        engine, _, _, _ = deepspeed_tpu.initialize(
            config={"train_batch_size": 8, "steps_per_print": 10 ** 6,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": stage},
                    "fp16": {"enabled": True, "initial_scale_power": 8}},
            model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(7)),
            mesh=make_mesh(pipeline_parallel_size=2))
        return run_fused(engine)

    np.testing.assert_allclose(run(2), run(1), rtol=2e-3, atol=1e-3)


def test_stage2_shrinks_grad_accumulator():
    """The point of stage 2: the LIVE grad accumulator a device holds
    between micro-steps is the 1/dp flat partition, not a replicated
    full-size fp32 grad tree.  Measured on real device buffers (the
    split API holds the accumulator across backward() calls)."""
    from test_zero_memory import device_bytes

    dev = jax.devices()[0]
    e1, e2 = make_engine(1), make_engine(2)
    for e in (e1, e2):
        loss = e(*lm_batch(8))
        e.backward(loss)
    full = device_bytes(e1._acc, dev)
    part = device_bytes(e2._acc, dev)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(e1.params))
    dp = e2.dp_world_size
    assert full == 4 * n_params, (full, n_params)      # replicated fp32
    assert part == 4 * e2.flat_meta.padded // dp, part  # owned partition
    assert part <= full // dp + 4 * 128
    # both engines still step correctly from their accumulators
    e1.step()
    e2.step()
    assert e1.global_steps == 1 and e2.global_steps == 1


def test_stage2_checkpoint_resume(tmp_path):
    """Optimizer-state layout is identical to stage 1, so save/resume is
    unchanged — resumed trajectory matches the unbroken run."""
    ref = run_fused(make_engine(2), steps=6)
    saver = make_engine(2)
    run_fused(saver, steps=3)
    saver.save_checkpoint(str(tmp_path), tag="s2")
    resumed = make_engine(2)
    resumed.load_checkpoint(str(tmp_path), tag="s2")
    post = [float(resumed.train_batch(lm_batch(8, seed=i)))
            for i in (3, 4, 5)]
    np.testing.assert_allclose(post, ref[3:], rtol=1e-5)


def test_stage2_with_param_groups():
    """Stage 2 x param_groups: the per-element gid expansion applies to
    the per-micro scattered partition — an lr=0 group stays frozen."""
    model = tiny_gpt2()
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 16, "gradient_accumulation_steps": 2,
                "steps_per_print": 10 ** 6,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "bf16": {"enabled": True}},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(7)),
        param_groups=[{"params": "wpe", "lr": 0.0}],
        mesh=make_mesh())
    init_wpe = np.asarray(model.init_params(
        jax.random.PRNGKey(7))["wpe"], np.float32)
    for i in range(2):
        engine.train_batch(lm_batch(16, seed=i))
    got = np.asarray(engine.params["wpe"], np.float32)
    np.testing.assert_allclose(got, init_wpe, atol=1e-3)
    assert not np.allclose(
        np.asarray(engine.params["wte"], np.float32),
        np.asarray(model.init_params(jax.random.PRNGKey(7))["wte"],
                   np.float32), atol=1e-4)


@pytest.mark.fast
def test_stage4_rejected():
    # stage 3 exists now (tests/test_zero3.py); the config guard moves to
    # the first unimplemented stage
    with pytest.raises(DeepSpeedConfigError, match="stage"):
        make_engine(4)
