"""Self-contained BERT-style wordpiece tokenization: trainer + tokenizer.

The reference's BERT recipe tokenizes with the standard BERT wordpiece
vocabulary (docs/_tutorials/bert-pretraining.md:289-305 fine-tunes
bert-large on SQuAD; tests/model/BingBertSquad drives the real-text
pipeline).  This container has no network egress, so instead of a
downloaded ``vocab.txt`` the framework owns the whole pipeline:

* ``BasicTokenizer`` — BERT's pre-tokenization (whitespace split,
  punctuation isolation, lowercasing, accent stripping) with CHARACTER
  OFFSETS into the original text preserved for every token, which is what
  SQuAD span extraction needs (predicted token spans map back to exact
  answer substrings).
* ``WordpieceTokenizer`` — greedy longest-match-first sub-word split with
  ``##`` continuation pieces, identical matching semantics to BERT's.
* ``train_wordpiece`` — a wordpiece-likelihood trainer (merge the symbol
  pair maximising ``count(ab) / (count(a)·count(b))``, the scoring rule
  of the original wordpiece algorithm) so a vocabulary can be built from
  any corpus in-process, deterministically.
* ``Vocab`` — token↔id table with BERT's special tokens and
  ``vocab.txt`` save/load (one token per line, id = line number).

Everything is pure Python on the host (tokenization is IO-side work; the
TPU sees int32 ids), with no dependency beyond the standard library.
"""

from __future__ import annotations

import collections
import unicodedata
from typing import Dict, Iterable, List, Sequence, Tuple

PAD_TOKEN = "[PAD]"
UNK_TOKEN = "[UNK]"
CLS_TOKEN = "[CLS]"
SEP_TOKEN = "[SEP]"
MASK_TOKEN = "[MASK]"
SPECIAL_TOKENS = (PAD_TOKEN, UNK_TOKEN, CLS_TOKEN, SEP_TOKEN, MASK_TOKEN)


def _is_whitespace(ch: str) -> bool:
    return ch.isspace() or unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    return unicodedata.category(ch).startswith("C") and ch not in "\t\n\r"


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # BERT rule: ASCII non-alnum blocks count as punctuation too ($, ~)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def normalize_word(word: str, do_lower_case: bool = True) -> str:
    """Lowercase + strip combining accents (BERT's run_strip_accents)."""
    if do_lower_case:
        word = word.lower()
    out = []
    for ch in unicodedata.normalize("NFD", word):
        if unicodedata.category(ch) != "Mn":
            out.append(ch)
    return "".join(out)


class BasicTokenizer:
    """Whitespace + punctuation pre-tokenizer with original-text offsets.

    ``tokenize_with_offsets(text)`` returns ``(tokens, spans)`` where
    ``spans[i] = (start, end)`` indexes the ORIGINAL string such that
    ``text[start:end]`` is the surface form of token ``i`` (tokens
    themselves are normalized — lowercased, accents stripped)."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize_with_offsets(self, text: str):
        tokens: List[str] = []
        spans: List[Tuple[int, int]] = []
        start = None
        for i, ch in enumerate(text):
            if _is_whitespace(ch) or _is_control(ch):
                if start is not None:
                    tokens.append(text[start:i])
                    spans.append((start, i))
                    start = None
            elif _is_punctuation(ch):
                if start is not None:
                    tokens.append(text[start:i])
                    spans.append((start, i))
                    start = None
                tokens.append(ch)
                spans.append((i, i + 1))
            else:
                if start is None:
                    start = i
        if start is not None:
            tokens.append(text[start:])
            spans.append((start, len(text)))
        tokens = [normalize_word(t, self.do_lower_case) for t in tokens]
        return tokens, spans

    def tokenize(self, text: str) -> List[str]:
        return self.tokenize_with_offsets(text)[0]


class WordpieceTokenizer:
    """Greedy longest-match-first wordpiece split (BERT semantics).

    A word→pieces memo backs ``tokenize``: natural text is Zipf
    distributed, so corpus featurization hits the cache for the vast
    majority of calls.  (A ctypes C matcher was measured and rejected:
    per-word Python↔C marshalling costs ~4× more than the dict-lookup
    match loop it replaces, even batched.)"""

    def __init__(self, vocab: Dict[str, int], unk_token: str = UNK_TOKEN,
                 max_input_chars_per_word: int = 100,
                 cache_size: int = 1 << 17):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word
        self._cache: Dict[str, Tuple[str, ...]] = {}
        self._cache_size = cache_size

    def tokenize(self, word: str) -> List[str]:
        hit = self._cache.get(word)
        if hit is not None:
            return list(hit)
        if len(word) > self.max_input_chars_per_word or not word:
            return [self.unk_token]
        pieces: List[str] = []
        lo = 0
        while lo < len(word):
            hi = len(word)
            piece = None
            while lo < hi:
                sub = word[lo:hi]
                if lo > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                hi -= 1
            if piece is None:
                pieces = [self.unk_token]
                break
            pieces.append(piece)
            lo = hi
        if len(self._cache) < self._cache_size:
            self._cache[word] = tuple(pieces)
        return pieces


class Vocab:
    """token↔id table; ids are dense, specials first (vocab.txt order)."""

    def __init__(self, tokens: Sequence[str]):
        self.id_to_token = list(tokens)
        self.token_to_id = {t: i for i, t in enumerate(self.id_to_token)}
        if len(self.token_to_id) != len(self.id_to_token):
            raise ValueError("duplicate tokens in vocabulary")

    def __len__(self):
        return len(self.id_to_token)

    def __contains__(self, tok):
        return tok in self.token_to_id

    def id(self, tok: str) -> int:
        return self.token_to_id.get(tok, self.token_to_id[UNK_TOKEN])

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for tok in self.id_to_token:
                f.write(tok + "\n")

    @classmethod
    def load(cls, path: str) -> "Vocab":
        with open(path) as f:
            return cls([line.rstrip("\n") for line in f if line.strip()])


class BertTokenizer:
    """The full BERT pipeline: basic split → wordpiece, id encoding, and
    offset-preserving tokenization for span tasks."""

    def __init__(self, vocab: Vocab, do_lower_case: bool = True):
        self.vocab = vocab
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(vocab.token_to_id)

    def tokenize_with_offsets(self, text: str):
        """(pieces, spans): wordpiece tokens with (start, end) character
        offsets into ``text``.  Sub-word offsets are exact when
        normalization preserves length (all of ASCII); for words it
        shortens (stripped accents) offsets are clamped to the word."""
        words, wspans = self.basic.tokenize_with_offsets(text)
        pieces, spans = [], []
        for word, (ws, we) in zip(words, wspans):
            subs = self.wordpiece.tokenize(word)
            off = 0
            for sub in subs:
                n = len(sub) - 2 if sub.startswith("##") else len(sub)
                if sub == UNK_TOKEN:
                    n = we - ws - off
                lo = min(ws + off, we)
                hi = min(lo + n, we)
                pieces.append(sub)
                spans.append((lo, hi))
                off += n
        return pieces, spans

    def tokenize(self, text: str) -> List[str]:
        return self.tokenize_with_offsets(text)[0]

    def encode(self, text: str) -> List[int]:
        return [self.vocab.id(t) for t in self.tokenize(text)]

    @property
    def pad_id(self):
        return self.vocab.id(PAD_TOKEN)

    @property
    def cls_id(self):
        return self.vocab.id(CLS_TOKEN)

    @property
    def sep_id(self):
        return self.vocab.id(SEP_TOKEN)


# ------------------------------------------------------------------ training

def train_wordpiece(texts: Iterable[str], vocab_size: int,
                    do_lower_case: bool = True,
                    min_pair_count: int = 2) -> Vocab:
    """Train a wordpiece vocabulary from raw text, deterministically.

    Classic wordpiece objective: starting from characters (continuations
    prefixed ``##``), repeatedly merge the adjacent symbol pair with the
    highest likelihood score ``count(ab) / (count(a) · count(b))`` until
    ``vocab_size`` symbols exist or no pair clears ``min_pair_count``.
    Ties break lexicographically so training is order-independent.
    """
    basic = BasicTokenizer(do_lower_case)
    word_freq: collections.Counter = collections.Counter()
    for text in texts:
        for w in basic.tokenize(text):
            if w:
                word_freq[w] += 1

    # word type → list of current symbols
    words = {w: [w[0]] + ["##" + c for c in w[1:]]
             for w in word_freq}
    alphabet = sorted({s for syms in words.values() for s in syms})
    vocab = list(SPECIAL_TOKENS) + alphabet
    have = set(vocab)

    def count_stats():
        sym_count: collections.Counter = collections.Counter()
        pair_count: collections.Counter = collections.Counter()
        for w, syms in words.items():
            f = word_freq[w]
            for s in syms:
                sym_count[s] += f
            for a, b in zip(syms, syms[1:]):
                pair_count[(a, b)] += f
        return sym_count, pair_count

    sym_count, pair_count = count_stats()
    while len(vocab) < vocab_size:
        best, best_score = None, 0.0
        for (a, b), c in pair_count.items():
            if c < min_pair_count:
                continue
            score = c / (sym_count[a] * sym_count[b])
            if (score > best_score
                    or (score == best_score and best is not None
                        and (a, b) < best)):
                best, best_score = (a, b), score
        if best is None:
            break
        a, b = best
        merged = a + b[2:] if b.startswith("##") else a + b
        if merged not in have:
            vocab.append(merged)
            have.add(merged)
        # rewrite affected word types, update counts incrementally
        for w, syms in words.items():
            if a not in syms:
                continue
            f = word_freq[w]
            i, out, changed = 0, [], False
            while i < len(syms):
                if (i + 1 < len(syms) and syms[i] == a
                        and syms[i + 1] == b):
                    out.append(merged)
                    i += 2
                    changed = True
                else:
                    out.append(syms[i])
                    i += 1
            if not changed:
                continue
            for s in syms:
                sym_count[s] -= f
            for pa, pb in zip(syms, syms[1:]):
                pair_count[(pa, pb)] -= f
            for s in out:
                sym_count[s] += f
            for pa, pb in zip(out, out[1:]):
                pair_count[(pa, pb)] += f
            words[w] = out
    return Vocab(vocab[:vocab_size] if len(vocab) > vocab_size else vocab)


# --------------------------------------------------------- MLM pretrain data

def build_mlm_arrays(texts: Iterable[str], tokenizer: BertTokenizer,
                     seq_len: int = 128, max_predictions: int = 20,
                     masked_lm_prob: float = 0.15, seed: int = 0,
                     n_samples: int = None):
    """Pre-tokenized BERT masked-LM pretraining arrays from raw text — the
    bing_bert data-pipeline analog (reference `bert-pretraining.md` data
    section), producing exactly the 6-field batch format
    ``BertForPreTraining`` consumes:

    ``(input_ids, input_mask, token_type_ids, masked_positions,
    masked_ids, masked_weights)``, each ``[N, ...]`` int32/float32.

    Documents tokenize once, pack greedily into ``seq_len``-2 windows
    ([CLS] ... [SEP]), and mask with the published 80/10/10 recipe (mask /
    random / keep) at ``masked_lm_prob`` capped at ``max_predictions``.
    Save with ``deepspeed_tpu.data.FileDataset.save(dir, **fields)`` for
    the memmap-backed file path."""
    import numpy as np
    rng = np.random.default_rng(seed)
    cls_id, sep_id = tokenizer.cls_id, tokenizer.sep_id
    mask_id = tokenizer.vocab.id(MASK_TOKEN)
    vocab_size = len(tokenizer.vocab)

    # tokenize + pack
    body = seq_len - 2
    stream: List[int] = []
    windows = []
    for text in texts:
        ids = tokenizer.encode(text)
        stream.extend(ids)
        while len(stream) >= body:
            windows.append(stream[:body])
            stream = stream[body:]
            if n_samples is not None and len(windows) >= n_samples:
                break
        if n_samples is not None and len(windows) >= n_samples:
            break
    if stream and (n_samples is None or len(windows) < n_samples):
        windows.append(stream)

    N = len(windows)
    input_ids = np.zeros((N, seq_len), np.int32)
    input_mask = np.zeros((N, seq_len), np.int32)
    token_type = np.zeros((N, seq_len), np.int32)
    positions = np.zeros((N, max_predictions), np.int32)
    masked_ids = np.zeros((N, max_predictions), np.int32)
    weights = np.zeros((N, max_predictions), np.float32)

    for i, win in enumerate(windows):
        toks = [cls_id] + list(win) + [sep_id]
        L = len(toks)
        input_ids[i, :L] = toks
        input_mask[i, :L] = 1
        # candidate positions exclude [CLS]/[SEP]
        cand = np.arange(1, L - 1)
        n_pred = min(max_predictions,
                     max(1, int(round(len(cand) * masked_lm_prob))))
        picked = rng.choice(cand, size=min(n_pred, len(cand)),
                            replace=False)
        picked.sort()
        for j, pos in enumerate(picked):
            positions[i, j] = pos
            masked_ids[i, j] = input_ids[i, pos]
            weights[i, j] = 1.0
            r = rng.random()
            if r < 0.8:
                input_ids[i, pos] = mask_id
            elif r < 0.9:
                input_ids[i, pos] = rng.integers(0, vocab_size)
            # else: keep the original token
    return {"input_ids": input_ids, "input_mask": input_mask,
            "token_type_ids": token_type, "masked_positions": positions,
            "masked_ids": masked_ids, "masked_weights": weights}
