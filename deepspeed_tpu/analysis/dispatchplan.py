"""Dispatch-cost pass — the static host timeline of one optimizer step.

WALLCLOCK §7 pins ~35 ms/step of host-boundary work at gas=8 — program
dispatches, deliberate fences, host↔device staging — a FIXED cost
gradient accumulation cannot amortize and the known enemy of ROADMAP
item 4's multi-step driver.  Until this pass the host boundary was only
observable by running (the fences.py counter, the dispatch
microbenches); here it becomes a static prediction: walk the engine's
configuration (program shape, gas, spool window, skip contract, report
cadence) and emit the per-step host timeline, priced in milliseconds by
the :class:`~.profiles.BackendProfile` dispatch-overhead constants.

Event classes:

* **dispatch**  — one compiled-program launch (runtime call + argument
  marshalling; cost scales with the argument leaf count);
* **fence**     — a deliberate host wait on device data.  Every fence the
  engine takes on purpose routes through ``observability/fences.py``, so
  the prediction here is CHECKABLE: :class:`FenceModel` reproduces the
  pinned counter exactly over an N-step run
  (tests/test_dispatch_stability.py — prediction drift is a test
  failure);
* **transfer**  — host→device staging (batch feeding, hyper staging);
* **callback**  — an in-graph host crossing (the telemetry spool drain —
  once per report window, never per step).

Findings ride the PR 2 report tree under ``dispatch.*``:

``dispatch.report``            (info)    the priced timeline roll-up.
``dispatch.fence-per-step``    (warning) a deliberate fence on EVERY
    boundary at steady state — the spool exists to remove these
    (``observability.report_window``); the fp16/nan-sentinel overflow
    read with an LR scheduler is the documented exception.
``dispatch.callback-per-step`` (warning) ``report_window: 1`` turns the
    once-per-window drain into a per-step host crossing.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax

from deepspeed_tpu.analysis import profiles as prof_mod
from deepspeed_tpu.analysis import report as R


@dataclasses.dataclass
class DispatchEvent:
    """One host-boundary event class on the per-step timeline."""

    kind: str                   # dispatch | fence | transfer | callback
    label: str
    per_step: float             # occurrences per optimizer step (may be
                                # fractional: per-window events amortize)
    n_leaves: int = 0           # argument leaves (dispatch marshalling)
    bytes_per: int = 0          # payload bytes (transfers)
    note: str = ""
    #: False = a data dependency the design cannot remove (the serving
    #: sampler's logits read): priced and counted, but never warned —
    #: warning noise on unremovable fences would desensitize readers to
    #: the genuinely fixable ones
    removable: bool = True

    def cost_ms(self, profile: Optional[prof_mod.BackendProfile]
                ) -> Optional[float]:
        """Predicted host ms per optimizer step for this event class."""
        if profile is None:
            return None
        if self.kind == "dispatch":
            each = (profile.dispatch_us
                    + self.n_leaves * profile.dispatch_leaf_us) / 1e3
        elif self.kind == "fence":
            # round-trip latency + the payload the host actually reads
            # back (the serving logits read moves 4*vocab*slots bytes per
            # iteration — at real vocab sizes the copy, not the sync,
            # dominates)
            each = (profile.fence_us / 1e3
                    + self.bytes_per / (profile.h2d_gibps * (1 << 30))
                    * 1e3)
        elif self.kind == "callback":
            each = profile.callback_us / 1e3
        else:                   # transfer: staging call + wire bytes
            each = (profile.dispatch_us / 1e3
                    + self.bytes_per / (profile.h2d_gibps * (1 << 30))
                    * 1e3)
        return self.per_step * each


@dataclasses.dataclass
class FenceModel:
    """Exact deliberate-fence arithmetic for an N-step run — the static
    twin of the ``observability.fences.FENCE_COUNT`` counter.

    ``per_boundary`` fences fire on every optimizer boundary (the
    fp16/nan-sentinel overflow read, the split-API TensorBoard loss
    read, wall-clock-breakdown timer syncs).  The throughput reporter
    additionally fences on report boundaries (``ThroughputTimer.stop``:
    ``local_step % steps_per_output == 0`` once past ``start_step``) —
    but only when the spool is off (with the spool on the engine passes
    ``sync_on=None`` and goodput rides the drain timestamps) AND
    something drives the timer's ``start()`` — the engine dataloader
    does, a custom loop feeding ``train_batch`` directly does not.
    ``flush_fences`` counts the synchronous spool flush the engine takes
    at run end / preemption drain.

    ``block_steps`` > 1 models the K-fused multi-step driver:
    ``per_boundary`` fences fire once per K-step BLOCK (the engine reads
    the whole ``[K]`` skip vector in one fence at the block edge), so
    over N steps the count is ``N // K`` blocks' worth — the K×
    amortization this PR exists for.  The reporter never fences at
    K > 1 (``train_many`` always passes ``sync_on=None``)."""

    per_boundary: int = 0
    tput_report: bool = False
    steps_per_output: int = 0
    start_step: int = 2
    flush_fences: int = 0       # per flush_telemetry() call, not per step
    block_steps: int = 1        # boundaries fused per dispatch (K)

    def count(self, n_steps: int, prior_boundaries: int = 0,
              flushes: int = 0) -> int:
        """Predicted fence-counter delta over ``n_steps`` boundaries
        starting after ``prior_boundaries`` completed ones.  With
        ``block_steps`` > 1, ``n_steps`` should cover whole blocks (the
        engine only ever completes whole dispatches); a ragged remainder
        is floored — fences fire at block EDGES only."""
        if self.block_steps > 1:
            total = (n_steps // self.block_steps) * self.per_boundary
            return total + flushes * self.flush_fences
        total = n_steps * self.per_boundary
        if self.tput_report and self.steps_per_output > 0:
            for b in range(prior_boundaries + 1,
                           prior_boundaries + n_steps + 1):
                if b > self.start_step and \
                        b % self.steps_per_output == 0:
                    total += 1
        return total + flushes * self.flush_fences

    def per_step_steady(self) -> float:
        """Average fences per boundary at steady state (report cadence
        and K-block amortization folded in)."""
        rate = float(self.per_boundary) / max(1, self.block_steps)
        if self.block_steps <= 1 and self.tput_report \
                and self.steps_per_output > 0:
            rate += 1.0 / self.steps_per_output
        return rate


@dataclasses.dataclass
class DispatchPlan:
    """The static host timeline of one optimizer step (or one serving
    iteration), priced against a backend profile."""

    subject: str
    events: List[DispatchEvent]
    fence_model: FenceModel
    profile: Optional[prof_mod.BackendProfile] = None
    #: predicted executables for this program family
    #: (stability.ExecutablePrediction), carried for the JSON artifact
    executables: Optional[object] = None

    def per_kind(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for e in self.events:
            out[e.kind] = out.get(e.kind, 0.0) + e.per_step
        return out

    def host_ms_per_step(self) -> Optional[float]:
        if self.profile is None:
            return None
        return sum(e.cost_ms(self.profile) or 0.0 for e in self.events)

    def fences_per_step(self) -> float:
        return self.fence_model.per_step_steady()

    def predict_fences(self, n_steps: int, prior_boundaries: int = 0,
                       flushes: int = 0) -> int:
        return self.fence_model.count(n_steps,
                                      prior_boundaries=prior_boundaries,
                                      flushes=flushes)

    # ------------------------------------------------------------ rendering
    def format_summary(self) -> str:
        pk = self.per_kind()
        t = self.host_ms_per_step()
        t_s = f", predicted host time {t:.3f} ms/step" if t is not None \
            else ""
        return (f"host/step: {pk.get('dispatch', 0):g} dispatch(es), "
                f"{self.fences_per_step():g} fence(s), "
                f"{pk.get('transfer', 0):g} transfer(s), "
                f"{pk.get('callback', 0):g} callback(s){t_s}")

    def format_table(self) -> str:
        name = self.profile.name if self.profile else "<none>"
        lines = [f"dispatch plan [{self.subject}]  profile {name}",
                 f"{'kind':<9} {'event':<22} {'per step':>9} "
                 f"{'ms/step':>9}  note"]
        for e in self.events:
            c = e.cost_ms(self.profile)
            lines.append(
                f"{e.kind:<9} {e.label:<22} {e.per_step:>9.3g} "
                f"{(f'{c:9.4f}' if c is not None else '        -')}  "
                f"{e.note}")
        t = self.host_ms_per_step()
        if t is not None:
            lines.append(f"{'total':<9} {'':<22} {'':>9} {t:>9.4f}")
        return "\n".join(lines)

    def to_report(self) -> R.Report:
        rep = R.Report(subject=self.subject)
        rep.add("dispatch.report", R.INFO, self.format_summary(),
                path=self.subject, pass_name="dispatch")
        steady = [e for e in self.events
                  if e.kind == "fence" and e.per_step >= 1.0
                  and e.removable]
        if steady:
            names = ", ".join(e.label for e in steady)
            rep.add(
                "dispatch.fence-per-step", R.WARNING,
                f"{self.subject} takes {sum(e.per_step for e in steady):g} "
                f"deliberate host fence(s) on EVERY step ({names}): each "
                f"one serializes host dispatch with device execution — a "
                f"fixed per-step cost gradient accumulation cannot "
                f"amortize (WALLCLOCK §7).  The metric spool removes the "
                f"per-boundary reads (observability.report_window); the "
                f"fp16/nan-sentinel overflow read WITH an LR scheduler is "
                f"the documented exception (docs/observability.md)",
                path=self.subject, pass_name="dispatch")
        for e in self.events:
            if e.kind == "callback" and e.per_step >= 1.0:
                rep.add(
                    "dispatch.callback-per-step", R.WARNING,
                    f"{self.subject}: {e.label} crosses the host on every "
                    f"step (report_window=1 turns the once-per-window "
                    f"drain into a per-step crossing) — raise "
                    f"observability.report_window",
                    path=self.subject, pass_name="dispatch")
        return rep

    def to_json(self) -> dict:
        out = {
            "subject": self.subject,
            "profile": self.profile.name if self.profile else None,
            "predicted_host_ms_per_step": self.host_ms_per_step(),
            "fences_per_step": self.fences_per_step(),
            "per_kind": self.per_kind(),
            "events": [{
                "kind": e.kind, "label": e.label, "per_step": e.per_step,
                "n_leaves": e.n_leaves, "bytes_per": e.bytes_per,
                "ms_per_step": e.cost_ms(self.profile), "note": e.note,
            } for e in self.events],
            "fence_model": {
                "per_boundary": self.fence_model.per_boundary,
                "tput_report": self.fence_model.tput_report,
                "steps_per_output": self.fence_model.steps_per_output,
                "start_step": self.fence_model.start_step,
                "flush_fences": self.fence_model.flush_fences,
                "block_steps": self.fence_model.block_steps,
            },
        }
        if self.executables is not None:
            out["executables"] = self.executables.to_json()
        return out


# -------------------------------------------------------------- byte helpers

def _tree_bytes(tree) -> int:
    # memplan.nbytes is the ONE byte model for the analysis package
    # (symbolic-dim guards, abstract-leaf handling)
    from deepspeed_tpu.analysis import memplan
    return sum(memplan.nbytes(leaf)
               for leaf in jax.tree_util.tree_leaves(tree))


def _n_leaves(args) -> int:
    return sum(len(jax.tree_util.tree_leaves(a)) for a in args)


# ------------------------------------------------------------- engine plans

def plan_engine_dispatch(engine, batch, fused: bool = True,
                         profile: Optional[prof_mod.BackendProfile] = None,
                         steps_per_dispatch: Optional[int] = None
                         ) -> DispatchPlan:
    """Static host timeline of one optimizer step for ``batch``'s format.

    Models exactly what the engine's hot path does per boundary: the
    program dispatch(es), the deliberate fences (cross-checked against
    the ``fences.py`` counter by the contract test), the host→device
    stagings, and the spool's once-per-window drain crossing.

    ``batch`` follows the matching call protocol: the FULL effective
    batch for ``fused=True`` (what ``train_batch()`` takes — one staging
    per step) and ONE MICRO batch for ``fused=False`` (what ``forward()``
    takes — ``gas`` stagings per step), which is exactly what the
    engine's build-time gate passes from each path.

    ``steps_per_dispatch`` (default: the engine's configured K) > 1
    prices the fused multi-step driver: ONE ``train_many`` dispatch per
    K optimizer steps, the skip-contract fence once per BLOCK, and the
    reporter fence gone — the amortization the contract test verifies
    against the runtime counters."""
    from deepspeed_tpu import analysis
    from deepspeed_tpu.analysis import stability

    if profile is None:
        profile = prof_mod.default_profile()
    batch = tuple(batch) if isinstance(batch, (tuple, list)) else (batch,)
    gas = engine.gradient_accumulation_steps()
    spool = getattr(engine, "_spool", None)
    window = int(getattr(engine.config, "observability_report_window", 0))
    tele = engine._telemetry
    skip_contract = bool(engine.config.fp16_enabled
                         or engine._nan_sentinel)
    deferred = bool(skip_contract and tele.defers_overflow(engine))
    wcb = bool(engine.wall_clock_breakdown())
    has_writer = engine.summary_writer is not None
    has_sched = engine.lr_scheduler is not None
    n_groups = len(engine._group_defs)
    if steps_per_dispatch is None:
        steps_per_dispatch = int(getattr(engine, "steps_per_dispatch", 1))
    k = steps_per_dispatch if fused else 1

    events: List[DispatchEvent] = []
    per_boundary_fences = 0

    if fused and k > 1:
        # leaf count WITHOUT marshalling the real train_many tuple:
        # train_many_args stages the [K,4,G] hyper block (and, with a
        # scheduler, steps/restores it k-1 times) — device/scheduler
        # side effects a static pass must not take.  vs the fused
        # single-step tuple: same state/hyper leaves, +1 live flag,
        # +(k-1) extra batch trees.
        base = analysis.train_batch_args(engine, batch)
        n_leaves = (_n_leaves(base) + 1
                    + (k - 1) * len(jax.tree_util.tree_leaves(batch)))
        events.append(DispatchEvent(
            "dispatch", "train_many", 1.0 / k, n_leaves=n_leaves,
            note=f"K={k} fused optimizer steps in ONE program — the "
                 f"per-step dispatch amortized K×"))
        events.append(DispatchEvent(
            "transfer", "batch", 1.0, bytes_per=_tree_bytes(batch),
            note=f"K effective batches staged per dispatch (one per "
                 f"step; one staging CALL per {k} steps)"))
    elif fused:
        args = analysis.train_batch_args(engine, batch)
        events.append(DispatchEvent(
            "dispatch", "train_batch", 1.0, n_leaves=_n_leaves(args),
            note="fwd+bwd+boundary in ONE program (gas folds into the "
                 "scan)"))
        events.append(DispatchEvent(
            "transfer", "batch", 1.0, bytes_per=_tree_bytes(batch),
            note="full effective batch staged per step"))
    else:
        fb_args = (engine.params, engine.loss_scale_state.cur_scale, batch)
        events.append(DispatchEvent(
            "dispatch", "fwdbwd", float(gas), n_leaves=_n_leaves(fb_args),
            note="one fused fwd+bwd program per micro step"))
        n_grad_leaves = len(jax.tree_util.tree_leaves(engine.params))
        if gas > 1:
            events.append(DispatchEvent(
                "dispatch", "grad-accumulate",
                float((gas - 1) * n_grad_leaves),
                n_leaves=2,
                note="host-driven jnp.add per grad leaf per extra micro "
                     "step (the fused path folds this into the scan)"))
        st_args = analysis.step_args(
            engine, jax.tree_util.tree_map(lambda x: x, engine.params))
        events.append(DispatchEvent(
            "dispatch", "step", 1.0, n_leaves=_n_leaves(st_args),
            note="boundary update program"))
        events.append(DispatchEvent(
            "transfer", "batch", float(gas),
            bytes_per=_tree_bytes(batch),
            note="one micro batch staged per forward"))
        if has_writer and spool is None:
            per_boundary_fences += 1
            events.append(DispatchEvent(
                "fence", "tb-loss-read", 1.0,
                note="float(loss) for the TensorBoard train_loss scalar "
                     "(spooled when report_window >= 1)"))
        if wcb:
            per_boundary_fences += 2 * gas
            events.append(DispatchEvent(
                "fence", "wcb-timers", float(2 * gas),
                note="wall_clock_breakdown syncs backward_inner + "
                     "backward_reduce every micro step"))

    # hyper staging: ONE cached [4, G] device array; re-staged only when a
    # scheduler moved a value (engine._current_hypers).  The K-fused
    # driver stages the [K, 4, G] block once per dispatch instead.
    events.append(DispatchEvent(
        "transfer", "hypers", (1.0 / k if has_sched else 0.0),
        bytes_per=16 * max(1, n_groups) * k,
        note=("[K, 4, G] prospective rows staged per dispatch"
              if k > 1 else
              "[4, G] stacked hypers; 0 transfers when no scheduler "
              "moves the values")))

    if skip_contract and not deferred:
        per_boundary_fences += 1
        events.append(DispatchEvent(
            "fence", "overflow-read", 1.0 / k,
            note=("fp16/nan-sentinel skip contract host read"
                  if k == 1 else
                  f"skip-contract [K] vector read once per {k}-step "
                  f"block (the per-step fence amortized K×)")
                 + (" (retained: LR scheduler gates on it — the "
                    "documented exception)" if spool is not None else
                    ("; deferred to the window drain when the spool is "
                     "on" if k == 1 else ""))))

    flush_fences = 0
    if spool is not None:
        if not fused:
            events.append(DispatchEvent(
                "dispatch", "spool-append", 1.0, n_leaves=6,
                note="split-API ring append (folded into train_batch on "
                     "the fused path)"))
        events.append(DispatchEvent(
            "dispatch", "spool-drain", 1.0 / max(1, window), n_leaves=2,
            note="drain program dispatch, once per report window"))
        events.append(DispatchEvent(
            "callback", "spool-drain", 1.0 / max(1, window),
            note="ONE async batched io_callback per report window"))
        flush_fences = 1

    # the throughput reporter only fences when something DRIVES the
    # timer: start() is called per batch by the engine dataloader
    # (data.py), never by the engine itself — a custom loop feeding
    # train_batch() directly never starts it, and stop() no-ops unstarted
    # (timer.py).  Condition on the loader (or a timer someone already
    # started), or predict_fences would count report fences FENCE_COUNT
    # never records.
    timer_driven = (getattr(engine, "training_dataloader", None) is not None
                    or bool(getattr(engine.tput_timer, "initialized",
                                    False)))
    # train_many always stops the reporter with sync_on=None (goodput
    # rides the telemetry windows at K > 1) — no report fence
    tput_report = spool is None and timer_driven and k == 1
    fence_model = FenceModel(
        per_boundary=per_boundary_fences,
        tput_report=tput_report,
        steps_per_output=int(getattr(engine.tput_timer, "steps_per_output",
                                     0) or 0),
        start_step=int(getattr(engine.tput_timer, "start_step", 2)),
        flush_fences=flush_fences,
        block_steps=k)
    if tput_report and fence_model.steps_per_output > 0:
        events.append(DispatchEvent(
            "fence", "tput-report",
            1.0 / fence_model.steps_per_output,
            note="throughput reporter fences on report boundaries only "
                 "(PR 1 window accounting)"))

    kind = ("train_many" if fused and k > 1
            else "train_batch" if fused else "fwdbwd+step")
    pred = stability.predict_executables(engine, [batch], train=True,
                                         fused=fused,
                                         steps_per_dispatch=k)
    return DispatchPlan(subject=kind, events=events,
                        fence_model=fence_model, profile=profile,
                        executables=pred)


def plan_serve_dispatch(engine,
                        profile: Optional[prof_mod.BackendProfile] = None
                        ) -> Dict[str, DispatchPlan]:
    """Static host timelines of the serving engine: one plan per program
    ("step" = one prefill admission / one decode iteration across all
    slots).  The per-iteration logits read is the sampler's data
    dependency — a priced, counted fence, not a removable one."""
    from deepspeed_tpu.analysis import stability

    if profile is None:
        profile = prof_mod.default_profile()
    pred = stability.predict_executables_serve(engine)
    slots = engine.num_slots
    vocab = int(getattr(engine.module.config, "vocab_size", 0) or 0)

    prefill_args = engine._program_args("prefill")
    prefill_events = [
        DispatchEvent("dispatch", "prefill", 1.0,
                      n_leaves=_n_leaves(prefill_args),
                      note="one executable per bucket for EVERY prompt "
                           "length and reuse offset (host-side bucket "
                           "padding; a prefix hit dispatches the "
                           "narrower tail bucket when the tail fits)"),
        DispatchEvent("transfer", "prompt", 1.0,
                      bytes_per=4 * (engine.prefill_bucket
                                     + engine.cache_spec.capacity),
                      note="padded [1, bucket] token ids + the slot's "
                           "[cap] page-table row map"),
    ]
    if int(getattr(engine, "spec_draft_tokens", 0) or 0) > 0:
        prefill_events.append(DispatchEvent(
            "dispatch", "draft_prefill", 1.0,
            n_leaves=_n_leaves(engine._program_args("draft_prefill")),
            note="the draft model's full-prompt prefill rides every "
                 "admission (no logits read — no extra fence)"))
    prefill_events.append(DispatchEvent(
        "fence", "logits-read", 1.0,
        bytes_per=4 * vocab, removable=False,
        note="sampler data dependency: the first generated token's "
             "distribution — ONE fence per admission even with the "
             "draft prefill riding along"))
    prefill = DispatchPlan(
        subject="prefill",
        events=prefill_events,
        fence_model=FenceModel(per_boundary=1),
        profile=profile, executables=pred)

    d = int(getattr(engine, "decode_iters_per_dispatch", 1))
    j = int(getattr(engine, "spec_draft_tokens", 0) or 0)
    if j > 0:
        # speculative block: ONE dispatch = J draft steps + verify +
        # acceptance; up to J+1 tokens per fence.  The amortization is
        # data-dependent (the accept rate), so the plan prices the
        # per-ITERATION boundary — one dispatch + one [J+1, slots]
        # token read — and the telemetry's spec_accept_rate converts it
        # to per-token cost at runtime.
        decode = DispatchPlan(
            subject="decode",
            events=[
                DispatchEvent("dispatch", "spec_step", 1.0,
                              n_leaves=_n_leaves(
                                  engine._program_args("spec_step")),
                              note=f"J={j} draft proposals + width-"
                                   f"{j + 1} target verify fused into "
                                   f"ONE dispatch (greedy acceptance "
                                   f"closes on device)"),
                DispatchEvent("transfer", "tokens+masks", 1.0,
                              bytes_per=13 * slots
                              + 8 * slots * engine.cache_spec.capacity,
                              note="per-slot token + active/eos/budget "
                                   "vectors + both page-table row maps"),
                DispatchEvent("fence", "tokens-read", 1.0,
                              bytes_per=5 * slots * (j + 1),
                              removable=False,
                              note=f"[J+1, slots] tokens + emitted "
                                   f"masks once per speculative "
                                   f"iteration — up to {j + 1} tokens "
                                   f"per fence at full acceptance"),
            ],
            fence_model=FenceModel(per_boundary=1),
            profile=profile, executables=pred)
        return {"prefill": prefill, "decode": decode}
    if d > 1:
        # D-fused decode: one dispatch + one TOKEN read (not logits —
        # the sampler ran on device) per D iterations
        decode = DispatchPlan(
            subject="decode",
            events=[
                DispatchEvent("dispatch", "decode_many", 1.0 / d,
                              n_leaves=_n_leaves(
                                  engine._program_args("decode_many")),
                              note=f"D={d} token steps fused into ONE "
                                   f"dispatch (greedy closes on device)"),
                DispatchEvent("transfer", "tokens+masks", 1.0 / d,
                              bytes_per=13 * slots,
                              note="per-slot token + active/eos/budget "
                                   "vectors, once per D-block"),
                DispatchEvent("fence", "tokens-read", 1.0 / d,
                              bytes_per=5 * slots * d, removable=False,
                              note=f"[D, slots] tokens + emitted masks "
                                   f"once per {d} iterations — the "
                                   f"per-token logits fence amortized "
                                   f"D× (and vocab× smaller)"),
            ],
            fence_model=FenceModel(per_boundary=1, block_steps=d),
            profile=profile, executables=pred)
    else:
        decode = DispatchPlan(
            subject="decode",
            events=[
                DispatchEvent("dispatch", "decode", 1.0,
                              n_leaves=_n_leaves(
                                  engine._program_args("decode")),
                              note="one token step across ALL slots"),
                DispatchEvent("transfer", "tokens+active", 1.0,
                              bytes_per=5 * slots,
                              note="per-slot input token + active mask"),
                DispatchEvent("fence", "logits-read", 1.0,
                              bytes_per=4 * vocab * slots,
                              removable=False,
                              note="sampler data dependency, every "
                                   "iteration"),
            ],
            fence_model=FenceModel(per_boundary=1),
            profile=profile, executables=pred)
    return {"prefill": prefill, "decode": decode}


def serve_predict_fences(plans: Dict[str, DispatchPlan], prefills: int,
                         decode_iters: int) -> int:
    """Predicted fence-counter delta for a serving run: one counted
    logits read per prefill admission and per decode iteration."""
    return (plans["prefill"].predict_fences(prefills)
            + plans["decode"].predict_fences(decode_iters))
