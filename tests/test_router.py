"""Serving fleet: least-loaded router + prefill/decode disaggregation
(deepspeed_tpu/inference/router.py, docs/inference.md "Fleet serving").

The load-bearing pins:

* **Placement invisibility** — a 2-replica fleet produces greedy token
  streams IDENTICAL to one replica on the same trace (batching
  invariance is what makes the router's admission decisions
  output-invisible), including THROUGH a replica eviction + resubmit.
* **KV handoff byte identity** — a prefill replica's exported page rows
  imported into a decode replica continue the request byte-identically
  (the PR 13 bitwise-page contract: same weights + same tokens ⇒ same
  page bytes), in memory and through the sealed chunk-container
  artifact with its named corruption errors.
* **Honest percentiles** — a request displaced by replica death
  re-enters the queue with its ORIGINAL arrival timestamp
  (``ContinuousScheduler.evacuate``/``submit(now=...)``), so
  queue-wait/TTFT keep measuring from the user's submit instead of
  silently resetting at the exact moment the fleet is slowest.
* **Restart detection** — ``/metrics`` on BOTH training and serving
  HealthServers exposes ``process_uptime_s`` and the launcher-fed
  monotonic ``replica_generation``, the router's restarted-vs-live
  replica signals.
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu import checkpoint
from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.inference import (ContinuousScheduler, FleetRouter,
                                     InferenceEngine, KVHandoff, Request,
                                     run_fleet, run_serve,
                                     synthetic_requests)
from deepspeed_tpu.models.gpt2 import GPT2
from deepspeed_tpu.observability import flightrec, schema
from deepspeed_tpu.observability import health as health_mod
from deepspeed_tpu.resilience import chaos

TINY = dict(vocab_size=128, max_seq_len=64, num_layers=2, hidden_size=64,
            num_heads=4)


def tiny_model():
    return GPT2.from_size("tiny", **TINY)


def serve_config(fleet=None, obs=None, **inf):
    base = {"max_slots": 4, "max_tokens": 64, "prefill_bucket": 32,
            "page_tokens": 8, "dtype": "float32"}
    base.update(inf)
    if fleet is not None:
        base["fleet"] = fleet
    if obs is not None:
        base["observability"] = obs
    return {"train_micro_batch_size_per_gpu": 1, "inference": base}


def build_engine(fleet=None, obs=None, **inf):
    return InferenceEngine(tiny_model(),
                           config=serve_config(fleet=fleet, obs=obs, **inf),
                           seed=0)


def trace(n=10, seed=0):
    return synthetic_requests(n, vocab=TINY["vocab_size"], seed=seed,
                              prompt_min=2, prompt_max=8, new_min=4,
                              new_max=14)


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


@pytest.fixture(scope="module")
def single_reference():
    """One replica's greedy token streams on the shared trace — the
    identity oracle every fleet arrangement must reproduce."""
    reqs = trace()
    eng = build_engine()
    res = run_serve(eng, reqs)["results"]
    return reqs, {r.rid: r.tokens for r in res}


# ---------------------------------------------------------------- fleet
def test_fleet_identity_vs_single(single_reference):
    reqs, ref = single_reference
    out = run_fleet([build_engine(), build_engine()], reqs, poll_s=0.02)
    assert {r.rid: r.tokens for r in out["results"]} == ref
    s = out["summary"]
    assert s["n_replicas"] == 2 and s["prefill_replicas"] == 0
    assert s["evictions"] == 0 and s["resubmits"] == 0
    assert s["requests"] == len(reqs)


def test_router_requires_an_engine():
    with pytest.raises(ValueError, match="at least one"):
        FleetRouter([])


def test_router_rejects_over_budget_request_at_submit():
    """Budget checks run at ROUTER intake: an over-budget request must
    be the submitter's loud error — handed to a driver thread it would
    kill the replica, be resubmitted by the eviction path, and serially
    wedge the whole fleet."""
    router = FleetRouter([build_engine()], poll_s=0.05)
    try:
        with pytest.raises(ValueError):
            router.submit(Request(rid=1, prompt=list(range(200)),
                                  max_new_tokens=4))
        assert router.submitted == 0
    finally:
        router.close()


def test_completion_from_evicted_replica_is_dropped(single_reference):
    """The zombie guard: a wedged replica that un-sticks AFTER eviction
    reports into the void — only the CURRENT owner's completion
    lands (a resubmitted request must not double-complete)."""
    from deepspeed_tpu.inference.router import _Flight
    from deepspeed_tpu.inference.scheduler import RequestResult
    router = FleetRouter([build_engine(), build_engine()], poll_s=0.05)
    rep0, rep1 = router.replicas
    req = Request(rid=7, prompt=[1, 2, 3], max_new_tokens=4)
    router._inflight[7] = _Flight(req, 0.0, rep1, "mixed")

    def result():
        return RequestResult(rid=7, tokens=[1, 2], finish_reason="length",
                             ttft_s=0.1, itl_s=[], prompt_len=3)

    router._complete(rep0, result())          # zombie: not the owner
    assert not router.results and 7 in router._inflight
    router._complete(rep1, result())          # the owner lands
    assert len(router.results) == 1 and 7 not in router._inflight
    router.close()


def test_prefix_affinity_routes_to_the_holding_replica():
    """Shared-prefix requests all land on the replica whose page-hash
    index holds the prefix — PR 13 reuse keeps paying at fleet scale
    instead of being diluted 1/N by load-balancing."""
    rng = np.random.default_rng(5)
    sys_prompt = rng.integers(0, TINY["vocab_size"], size=24).astype(
        int).tolist()          # 3 pages at page_tokens=8
    reqs = []
    for i in range(8):
        tail = rng.integers(0, TINY["vocab_size"], size=int(
            rng.integers(2, 6))).astype(int).tolist()
        reqs.append(Request(rid=i, prompt=sys_prompt + tail,
                            max_new_tokens=6))
    engines = [build_engine(), build_engine()]
    ref_eng = build_engine()
    ref = {r.rid: r.tokens for r in run_serve(ref_eng, [
        Request(rid=r.rid, prompt=list(r.prompt),
                max_new_tokens=r.max_new_tokens) for r in reqs])["results"]}
    out = run_fleet(engines, reqs, poll_s=0.02)
    assert {r.rid: r.tokens for r in out["results"]} == ref
    assert out["summary"]["affinity_hits"] > 0
    # the fleet-level reuse proof: pages were actually served from the
    # shared-prefix cache on the replica affinity kept routing to
    assert sum(e.pool.tokens_reused for e in engines) > 0


def test_affinity_off_records_no_hits(single_reference):
    reqs, ref = single_reference
    out = run_fleet([build_engine(), build_engine()], reqs,
                    poll_s=0.02, affinity=False)
    assert {r.rid: r.tokens for r in out["results"]} == ref
    assert out["summary"]["affinity_hits"] == 0


# ---------------------------------------------------- requeue semantics
def test_evacuate_preserves_original_timestamps():
    """Satellite fix: a request evicted by replica death re-enters the
    queue with its ORIGINAL arrival timestamp — TTFT/queue-wait keep
    anchoring at the user's submit, never silently resetting."""
    eng = build_engine()
    sched = ContinuousScheduler(eng)
    t_orig = time.perf_counter() - 5.0      # submitted "5 seconds ago"
    reqs = trace(6, seed=2)
    for r in reqs[:3]:
        sched.submit(r, now=t_orig)
    sched.step()                            # admit some into slots
    for r in reqs[3:]:
        sched.submit(r, now=t_orig)         # still queued
    assert sched.active > 0
    pairs = sched.evacuate()
    assert len(pairs) == 6
    assert all(t == t_orig for _, t in pairs)
    # in-flight first (they arrived before anything still queued)
    assert [r.rid for r, _ in pairs[:3]] == [r.rid for r in reqs[:3]]
    # the scheduler is left empty and reusable; pool pages released
    assert sched.active == 0 and sched.pending == 0
    assert eng.pool.gauges()["pages_in_use"] == 0
    # resubmission through submit(now=...) keeps measuring from t_orig
    eng2 = build_engine()
    sched2 = ContinuousScheduler(eng2)
    for r, t in pairs:
        sched2.submit(r, now=t)
    results = sched2.run()
    assert all(r.queue_wait_s >= 5.0 for r in results), \
        "queue wait must keep accruing from the ORIGINAL submit"
    assert all(r.ttft_s >= 5.0 for r in results)


def test_evacuate_returns_unimported_handoffs_as_requests():
    eng = build_engine(fleet={"disaggregate": True})
    sched = ContinuousScheduler(eng)
    spec = eng.cache_spec
    heads_g = spec.kv_heads_local * spec.mp_size
    k = np.zeros((spec.layers, 3, heads_g, spec.head_dim), np.float32)
    h = KVHandoff(req=Request(rid=1, prompt=[1, 2, 3], max_new_tokens=4),
                  prompt=[1, 2, 3], first_token=5, k=k, v=k.copy(),
                  n_tokens=3, t_enqueue=123.0, t_admit=124.0,
                  t_first_token=125.0)
    sched.submit_handoff(h)
    assert sched.pending == 1
    pairs = sched.evacuate()
    assert pairs == [(h.req, 123.0)]


# ------------------------------------------------------------- eviction
@pytest.mark.chaos
def test_eviction_chaos_end_to_end(single_reference, tmp_path):
    """Satellite: --chaos-stall style wedge mid-traffic → serve watchdog
    fires → /healthz 503 → router evicts + resubmits → every request
    completes with outputs identical to an unwedged run — and the wedged
    replica's flight-recorder dump loads and names the stalled decode
    dispatch."""
    reqs, ref = single_reference
    dump_dir = str(tmp_path / "dumps")

    def build_wd():
        return build_engine(obs={"watchdog_timeout_s": 0.4,
                                 "flight_recorder_dir": dump_dir})

    engines = [build_wd(), build_wd()]
    for e in engines:
        e.generate([reqs[0].prompt], max_new_tokens=2)
        e.reset()
    stall_at = max(e.decode_dispatches for e in engines) + 3
    chaos.configure(stall_step=stall_at, stall_s=30.0)
    try:
        out = run_fleet(engines, reqs, poll_s=0.02)
    finally:
        chaos.reset()
    assert {r.rid: r.tokens for r in out["results"]} == ref, \
        "greedy identity must survive eviction + resubmission"
    s = out["summary"]
    assert s["evictions"] >= 1 and s["resubmits"] >= 1
    # exactly one watchdog fired (one replica wedged), its dump loads
    # and the armed-region breadcrumb names the stalled decode
    dumps = [f for f in os.listdir(dump_dir) if "watchdog" in f]
    assert len(dumps) == 1, dumps
    d = flightrec.load_dump(os.path.join(dump_dir, dumps[0]))
    assert d["reason"] == "watchdog"
    kinds = {e["kind"] for e in d["entries"]}
    assert "serve_decode" in kinds, \
        f"the dump must name the stalled decode dispatch, got {kinds}"


def test_all_replicas_dead_is_an_error_not_a_hang(single_reference):
    reqs, _ = single_reference
    router = FleetRouter([build_engine()], poll_s=0.02)
    router.start()
    router.replicas[0].error = RuntimeError("driver died")
    with pytest.raises(RuntimeError, match="no progress"):
        router.serve(reqs[:2], timeout_s=30.0, stall_timeout_s=1.0)
    router.close()


# ----------------------------------------------------------- KV handoff
def test_export_import_continues_byte_identically(single_reference):
    """The disaggregation primitive in isolation: prefill on replica A,
    export the slot's KV rows, import into replica B, decode there —
    token stream identical to the single-replica run of the same
    request (the bitwise-page contract doing the heavy lifting)."""
    reqs, ref = single_reference
    req = max(reqs, key=lambda r: r.max_new_tokens)
    pre = build_engine(fleet={"disaggregate": True})
    dec = build_engine(fleet={"disaggregate": True})
    logits, reused = pre.admit(0, req.prompt, req.max_new_tokens)
    tok0 = int(np.argmax(np.asarray(logits, np.float32)))
    k, v, n = pre.export_kv(0)
    assert n == len(req.prompt)
    grant = dec.import_kv(0, req.prompt, k, v, req.max_new_tokens)
    assert grant is not None
    toks = [tok0]
    feed = np.zeros((dec.num_slots,), np.int32)
    active = np.zeros((dec.num_slots,), bool)
    while len(toks) < req.max_new_tokens:
        feed[0], active[0] = toks[-1], True
        step_logits = dec.decode(feed, active)
        toks.append(int(np.argmax(
            np.asarray(step_logits[0], np.float32))))
    assert toks == ref[req.rid]


def test_kv_handoff_artifact_roundtrip(tmp_path):
    path = str(tmp_path / "h.kvh")
    k = np.arange(2 * 3 * 4 * 5, dtype=np.float32).reshape(2, 3, 4, 5)
    v = -k
    meta = {"rid": 3, "prompt": [1, 2, 3], "max_new_tokens": 7,
            "eos_id": None, "first_token": 9, "n_tokens": 3,
            "t_enqueue": 1.0, "t_admit": 2.0, "t_first_token": 3.0}
    checkpoint.write_kv_handoff(path, k=k, v=v, meta=meta)
    meta2, k2, v2 = checkpoint.read_kv_handoff(path)
    assert meta2 == meta
    np.testing.assert_array_equal(k2, k)
    np.testing.assert_array_equal(v2, v)


def test_kv_handoff_corruption_raises_named_errors(tmp_path):
    path = str(tmp_path / "h.kvh")
    k = np.ones((1, 2, 2, 2), np.float32)
    checkpoint.write_kv_handoff(path, k=k, v=k, meta={"n_tokens": 2})
    # truncated payload: the memmap fault surfaces as a NAMED error
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[:len(data) // 2])
    with pytest.raises(checkpoint.CheckpointReadError):
        checkpoint.read_kv_handoff(path)
    # wrong artifact kind: schema is checked before any array view
    import pickle
    with open(path, "wb") as f:
        pickle.dump({"schema": "not.a.handoff"}, f)
    with pytest.raises(checkpoint.CheckpointReadError, match="schema"):
        checkpoint.read_kv_handoff(path)


def test_export_import_require_disaggregate_config():
    eng = build_engine()
    with pytest.raises(RuntimeError, match="disaggregate"):
        eng.export_kv(0)
    with pytest.raises(RuntimeError, match="disaggregate"):
        eng.import_kv(0, [1, 2], np.zeros((2, 2, 4, 16), np.float32),
                      np.zeros((2, 2, 4, 16), np.float32), 4)


def test_import_kv_validates_shape_and_dtype():
    eng = build_engine(fleet={"disaggregate": True})
    spec = eng.cache_spec
    heads_g = spec.kv_heads_local * spec.mp_size
    good = np.zeros((spec.layers, 3, heads_g, spec.head_dim), np.float32)
    with pytest.raises(ValueError, match="shape mismatch"):
        eng.import_kv(0, [1, 2, 3], good[:, :2], good, 4)
    with pytest.raises(ValueError, match="dtype"):
        eng.import_kv(0, [1, 2, 3], good.astype(np.float16),
                      good.astype(np.float16), 4)
    # v alone diverging must raise too — a silent numpy cast here would
    # corrupt value pages with no signal
    with pytest.raises(ValueError, match="v dtype"):
        eng.import_kv(0, [1, 2, 3], good, good.astype(np.float64), 4)
    with pytest.raises(ValueError, match="capacity"):
        toks = list(range(spec.capacity + 1))
        big = np.zeros((spec.layers, spec.capacity + 1, heads_g,
                        spec.head_dim), np.float32)
        eng.import_kv(0, toks, big, big, 4)


def test_corrupt_handoff_fails_one_request_not_the_replica(
        single_reference, monkeypatch):
    """A torn handoff artifact returns the ONE affected request to the
    router for a fresh prefill — the decode replica stays healthy, no
    eviction, and the re-derived outputs are identical (the documented
    'fails one request loudly' contract)."""
    reqs, ref = single_reference
    from deepspeed_tpu import checkpoint as ckpt_mod
    real = ckpt_mod.write_kv_handoff
    corrupted = []

    def corrupting(path, **kw):
        real(path, **kw)
        if not corrupted:               # torn file: first artifact only
            corrupted.append(path)
            data = open(path, "rb").read()
            with open(path, "wb") as f:
                f.write(data[:len(data) // 2])
        return path

    monkeypatch.setattr(ckpt_mod, "write_kv_handoff", corrupting)
    dec = build_engine(fleet={"disaggregate": True})
    pre = build_engine(fleet={"disaggregate": True})
    out = run_fleet([dec], reqs, prefill_engines=[pre], poll_s=0.02)
    assert corrupted, "the corruption injection never ran"
    assert {r.rid: r.tokens for r in out["results"]} == ref
    assert out["summary"]["evictions"] == 0
    # the displaced request re-prefilled: one extra handoff
    assert out["summary"]["handoffs"] == len(reqs) + 1


def test_disaggregated_fleet_identity_and_handoffs(single_reference):
    reqs, ref = single_reference
    dec = build_engine(fleet={"disaggregate": True})
    pre = build_engine(fleet={"disaggregate": True})
    out = run_fleet([dec], reqs, prefill_engines=[pre], poll_s=0.02)
    assert {r.rid: r.tokens for r in out["results"]} == ref
    assert out["summary"]["handoffs"] == len(reqs)
    assert out["summary"]["prefill_replicas"] == 1


def test_prefill_pool_death_degrades_to_mixed(single_reference):
    """Losing the WHOLE prefill pool must degrade the fleet to mixed
    serving (decode replicas are full engines and can prefill), not
    stall intake until the stall timeout fires."""
    reqs, ref = single_reference
    dec = build_engine(fleet={"disaggregate": True})
    pre = build_engine(fleet={"disaggregate": True})
    router = FleetRouter([dec], [pre], poll_s=0.02)
    try:
        router.start()
        router._evict(router.prefill_pool[0])
        out = router.serve(reqs, stall_timeout_s=30.0)
        assert {r.rid: r.tokens for r in out["results"]} == ref
        assert out["summary"]["handoffs"] == 0
    finally:
        router.close()


def test_fleet_without_shared_sink_honors_replica_jsonl(
        single_reference, tmp_path):
    """With no fleet-level JSONL, a replica's own configured
    observability stream must still be produced — the config knob
    cannot be silently ignored in fleet mode."""
    reqs, ref = single_reference
    path = str(tmp_path / "replica.jsonl")
    eng = build_engine(obs={"jsonl_path": path, "window_iters": 4})
    out = run_fleet([eng], reqs, poll_s=0.02)
    assert {r.rid: r.tokens for r in out["results"]} == ref
    events = [json.loads(l) for l in open(path)]
    assert any(e["schema"] == schema.SERVE_SCHEMA_ID for e in events)
    assert sum(e["schema"] == schema.REQUEST_SCHEMA_ID
               for e in events) == len(reqs)


def test_disaggregation_is_greedy_only():
    dec = build_engine(fleet={"disaggregate": True})
    pre = build_engine(fleet={"disaggregate": True})
    with pytest.raises(ValueError, match="greedy-only"):
        FleetRouter([dec], [pre], sampler=lambda logits: 0)


def test_disaggregation_requires_the_config_flag():
    with pytest.raises(ValueError, match="disaggregate"):
        FleetRouter([build_engine()], [build_engine()])


def test_disaggregation_requires_matching_cache_specs():
    """Handoff compatibility is a BUILD error: an ``import_kv``
    shape/dtype mismatch fires inside the decode replica's driver
    thread, where it reads as a wedge — the router would evict the
    replica, resubmit its neighbours, and a minimal 1+1 topology
    deadlocks into the stall timeout instead of naming the
    misconfiguration."""
    dec = build_engine(fleet={"disaggregate": True})
    pre = build_engine(fleet={"disaggregate": True}, dtype="bfloat16")
    with pytest.raises(ValueError, match="KV specs diverge"):
        FleetRouter([dec], [pre])


def test_router_removes_only_its_own_handoff_dir(tmp_path):
    """A router-created (mkdtemp) handoff dir is removed at close; a
    caller-provided dir is not the router's to remove."""
    router = FleetRouter([build_engine()], poll_s=0.05)
    own = router.handoff_dir
    router.close()
    assert not os.path.exists(own)
    given = str(tmp_path / "handoffs")
    router = FleetRouter([build_engine()], poll_s=0.05, handoff_dir=given)
    router.close()
    assert os.path.isdir(given)


def test_chaos_stall_ends_when_any_registered_watchdog_fires():
    """Multi-replica processes register EVERY replica's watchdog
    fire_event (chaos.add_stall_until): the stall lands in whichever
    replica dispatches first, and only that replica's watchdog reacts —
    a single registered event from another replica would burn the full
    stall_s."""
    ev_first, ev_stalled = threading.Event(), threading.Event()
    chaos.configure(stall_step=1, stall_s=30.0)
    chaos.add_stall_until(ev_first)      # replica 0: never fires
    chaos.add_stall_until(ev_stalled)    # replica 1: the stalled one
    ev_stalled.set()
    t0 = time.monotonic()
    chaos.maybe_stall(1)
    assert time.monotonic() - t0 < 5.0


def test_predicted_executables_include_handoff_programs():
    from deepspeed_tpu.analysis import stability
    plain = stability.predict_executables_serve(build_engine())
    dis = stability.predict_executables_serve(
        build_engine(fleet={"disaggregate": True}))
    names = {p[0] for p in dis.programs}
    assert {"export_kv", "import_kv"} <= names
    assert len(dis.programs) == len(plain.programs) + 2


# ------------------------------------------------------------ telemetry
def test_router_jsonl_validates_and_counts(single_reference, tmp_path):
    reqs, ref = single_reference
    path = str(tmp_path / "router.jsonl")
    out = run_fleet([build_engine(), build_engine()], reqs,
                    poll_s=0.02, jsonl_path=path)
    assert {r.rid: r.tokens for r in out["results"]} == ref
    problems = schema.validate_jsonl(path)
    assert not problems, problems[:3]
    events = [json.loads(l) for l in open(path)]
    router_evs = [e for e in events
                  if e["schema"] == schema.ROUTER_SCHEMA_ID]
    assert router_evs, "no router windows on the stream"
    last = router_evs[-1]
    assert last["requests_completed"] == len(reqs)
    assert last["n_replicas"] == 2
    assert set(last["per_replica"]) == {"0", "1"}
    for load in last["per_replica"].values():
        assert {"slots_in_use", "queue_depth", "free_pages",
                "healthy", "role"} <= set(load)
    # replica request events interleave on the SAME stream
    req_evs = [e for e in events
               if e["schema"] == schema.REQUEST_SCHEMA_ID]
    assert len(req_evs) == len(reqs)


def test_router_event_schema_negatives():
    base = {"schema": schema.ROUTER_SCHEMA_ID, "version": 1,
            "ts": 1.0, "window": 1, "n_replicas": 2,
            "healthy_replicas": 2, "prefill_replicas": 0,
            "requests_submitted": 4, "requests_completed": 2,
            "requests_inflight": 1, "queue_depth": 1, "tokens_out": 10,
            "tokens_per_sec": 5.0, "evictions": 0, "resubmits": 0,
            "handoffs": 0, "affinity_hits": 0, "ttft_p50_ms": 1.0,
            "ttft_p99_ms": 2.0, "queue_wait_p50_ms": 0.1,
            "queue_wait_p99_ms": 0.2, "per_replica": {}}
    assert schema.validate_router_event(base) is None
    assert schema.validate_any(base) is None
    bad = dict(base, healthy_replicas=3)
    assert "healthy_replicas" in schema.validate_router_event(bad)
    bad = dict(base, requests_completed=9)
    assert "requests_submitted" in schema.validate_router_event(bad)
    bad = dict(base)
    del bad["evictions"]
    assert schema.validate_router_event(bad) is not None
    bad = dict(base, resubmits=-1)
    assert "resubmits" in schema.validate_router_event(bad)


def test_validator_cli_handles_router_stream(tmp_path):
    import subprocess
    import sys
    ev = {"schema": schema.ROUTER_SCHEMA_ID, "version": 1, "ts": 1.0,
          "window": 1, "n_replicas": 1, "healthy_replicas": 1,
          "prefill_replicas": 0, "requests_submitted": 1,
          "requests_completed": 1, "requests_inflight": 0,
          "queue_depth": 0, "tokens_out": 4, "tokens_per_sec": None,
          "evictions": 0, "resubmits": 0, "handoffs": 0,
          "affinity_hits": 0, "ttft_p50_ms": None, "ttft_p99_ms": None,
          "queue_wait_p50_ms": None, "queue_wait_p99_ms": None,
          "per_replica": {"0": {"slots_in_use": 0}}}
    good = tmp_path / "router.jsonl"
    good.write_text(json.dumps(ev) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.observability", str(good)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "router" in proc.stdout
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(dict(ev, n_replicas=0)) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.observability", str(bad)],
        capture_output=True, text=True)
    assert proc.returncode == 2


# ------------------------------------------------------- live endpoints
def test_router_and_replica_endpoints(single_reference):
    """The fleet's own /healthz /status /metrics next to each replica's
    per-replica endpoints — the cross-host router protocol served over
    real HTTP from one process."""
    reqs, ref = single_reference
    router = FleetRouter([build_engine(), build_engine()],
                         health_port=18985,
                         replica_ports=[18986, 18987], poll_s=0.02)
    try:
        assert router.obs is not None and router.obs.port
        ports = [rep.port for rep in router.replicas]
        assert ports == [18986, 18987]
        out = router.serve(reqs)
        assert {r.rid: r.tokens for r in out["results"]} == ref
        with urllib.request.urlopen(
                f"http://127.0.0.1:{router.obs.port}/healthz",
                timeout=5) as r:
            assert r.getcode() == 200
        with urllib.request.urlopen(
                f"http://127.0.0.1:{router.obs.port}/status",
                timeout=5) as r:
            status = json.loads(r.read())
        assert status["n_replicas"] == 2
        assert status["requests_completed"] == len(reqs)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{router.obs.port}/metrics",
                timeout=5) as r:
            parsed = health_mod.parse_prometheus_text(r.read().decode())
        assert parsed["dstpu_healthy"] == 1
        assert parsed["dstpu_healthy_replicas"] == 2
        assert parsed["dstpu_tokens_out"] > 0
        assert parsed["dstpu_process_uptime_s"] > 0
        # each replica's own endpoint answers too (the router scrapes
        # these for admission)
        for port in ports:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                rep_metrics = health_mod.parse_prometheus_text(
                    r.read().decode())
            assert "dstpu_slots_in_use" in rep_metrics
            assert "dstpu_process_uptime_s" in rep_metrics
            assert "dstpu_replica_generation" in rep_metrics
    finally:
        router.close()


def test_uptime_and_generation_gauges():
    """Satellite: the restart-detection gauges on BOTH HealthServer
    facades — uptime resets and the launcher-fed generation ordinal
    increments on a relaunch."""
    assert health_mod.process_uptime_s() > 0
    # serving facade
    from deepspeed_tpu.inference.observability import ServeObservability
    obs = ServeObservability(build_engine(), port=0)
    m = obs.health_metrics()
    assert m["process_uptime_s"] > 0 and m["replica_generation"] == 0
    obs.close()
    # training facade (the Telemetry health_metrics the training
    # HealthServer renders) — a minimal stand-in carrying exactly the
    # state health_metrics reads
    from types import SimpleNamespace

    from deepspeed_tpu.observability import Telemetry
    tel = Telemetry.__new__(Telemetry)
    tel._lock = threading.Lock()
    tel._engine_ref = lambda: None
    tel.registry = SimpleNamespace(counters_snapshot=lambda: {})
    tel.healthy = lambda: True
    tel.last_window_event = tel.last_fleet_event = None
    m = tel.health_metrics()
    assert m["process_uptime_s"] > 0 and m["replica_generation"] == 0


def test_replica_generation_env(monkeypatch):
    monkeypatch.setenv(health_mod.ENV_REPLICA_GENERATION, "3")
    assert health_mod.replica_generation() == 3
    monkeypatch.setenv(health_mod.ENV_REPLICA_GENERATION, "garbage")
    assert health_mod.replica_generation() == 0
    monkeypatch.delenv(health_mod.ENV_REPLICA_GENERATION)
    assert health_mod.replica_generation() == 0


# --------------------------------------------------------- config guards
def test_fleet_config_guards():
    from deepspeed_tpu.config import DeepSpeedConfig

    def cfg(fleet):
        return DeepSpeedConfig(serve_config(fleet=fleet))

    ok = cfg({"replicas": 2, "prefill_replicas": 1, "disaggregate": True,
              "health_port": 9000, "poll_s": 0.1, "affinity": False,
              "handoff_dir": "/tmp/h", "jsonl_path": "/tmp/r.jsonl"})
    assert ok.inference_fleet_replicas == 2
    assert ok.inference_fleet_prefill_replicas == 1
    assert ok.inference_fleet_disaggregate is True
    assert ok.inference_fleet_affinity is False
    with pytest.raises(DeepSpeedConfigError, match="unknown"):
        cfg({"replica": 2})
    with pytest.raises(DeepSpeedConfigError, match="disaggregate"):
        cfg({"replicas": 2, "prefill_replicas": 1})
    with pytest.raises(DeepSpeedConfigError, match="DECODE"):
        cfg({"replicas": 2, "prefill_replicas": 2, "disaggregate": True})
    with pytest.raises(DeepSpeedConfigError, match="poll_s"):
        cfg({"poll_s": 0})
    with pytest.raises(DeepSpeedConfigError, match="65535"):
        cfg({"health_port": 70000})
    with pytest.raises(DeepSpeedConfigError, match=">= 0"):
        cfg({"replicas": -1})
    with pytest.raises(DeepSpeedConfigError, match="object"):
        cfg(17)
