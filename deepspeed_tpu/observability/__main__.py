"""JSONL event-log validator CLI.

``python -m deepspeed_tpu.observability <events.jsonl> [...]`` — validates
every line of each telemetry event log against the window schema
(observability/schema.py).  Exit codes: 0 = every file valid and
non-empty, 2 = any problem (the CI observability smoke job's gate).
Needs no jax — it is a pure-JSON check usable on artifact files anywhere.
"""

from __future__ import annotations

import argparse
import sys

from deepspeed_tpu.observability import schema


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.observability",
        description="Validate telemetry JSONL event logs "
                    "(schema %s v%d)" % (schema.SCHEMA_ID,
                                         schema.SCHEMA_VERSION))
    parser.add_argument("paths", nargs="+", help="JSONL event log(s)")
    args = parser.parse_args(argv)

    rc = 0
    for path in args.paths:
        problems = schema.validate_jsonl(path)
        if not problems:
            with open(path) as f:
                n = sum(1 for line in f if line.strip())
            print(f"{path}: OK ({n} event(s))")
            continue
        rc = 2
        for line_no, msg in problems:
            where = f"{path}:{line_no}" if line_no else path
            print(f"{where}: {msg}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
