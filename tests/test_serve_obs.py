"""Serving-replica observability (docs/observability.md "Serving view").

The load-bearing pins:

* **Trajectory neutrality** — greedy outputs and the deliberate-fence
  counter are IDENTICAL with the full observability stack on or off
  (request events, watchdog, detectors, endpoints are host-side only).
* **Per-request records** — one validator-clean
  ``dstpu.telemetry.request`` line per completed request, with the
  lifecycle breakdown consistent (ttft ≈ queue wait + prefill).
* **Per-request percentiles** — ``latency_summary``'s p50/p99 are
  derived from per-request records, so they no longer collapse to 0
  under fused decode (the old pooled per-token design's documented
  failure at D>1).
* **Schema evolution** — serve v1/v2 logs still validate next to v3 +
  request streams; the validator CLI exit-2 contract stays pinned.
* **Hang capture** — a stalled decode fires the serve watchdog:
  ``/healthz`` turns 503 (the fleet router's eviction signal) and a
  loadable flight-recorder dump names the stalled decode dispatch.
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.inference import (ContinuousScheduler, InferenceEngine,
                                     Request, ServeObservability,
                                     ServeTelemetry, kvcache, observability,
                                     run_serve, synthetic_requests)
from deepspeed_tpu.inference.scheduler import (RequestResult,
                                               latency_summary)
from deepspeed_tpu.models.gpt2 import GPT2
from deepspeed_tpu.observability import detectors, fences, flightrec, schema
from deepspeed_tpu.observability.health import (HealthServer,
                                                parse_prometheus_text)
from deepspeed_tpu.resilience import chaos

TINY = dict(vocab_size=128, max_seq_len=64, num_layers=2, hidden_size=64,
            num_heads=4)


def tiny_model():
    return GPT2.from_size("tiny", **TINY)


def serve_config(obs=None, **inf):
    base = {"max_slots": 3, "max_tokens": 32, "prefill_bucket": 16,
            "page_tokens": 32, "dtype": "float32"}
    base.update(inf)
    if obs is not None:
        base["observability"] = obs
    return {"train_micro_batch_size_per_gpu": 1, "inference": base}


def trace(n=5, seed=3):
    return synthetic_requests(n, vocab=TINY["vocab_size"], seed=seed,
                              prompt_min=3, prompt_max=10, new_min=3,
                              new_max=7)


@pytest.fixture(autouse=True)
def _clean_globals():
    chaos.reset()
    detectors.SERVE_COUNTERS.reset()
    yield
    chaos.reset()
    detectors.SERVE_COUNTERS.reset()


@pytest.fixture(scope="module")
def eng():
    return InferenceEngine(tiny_model(), config=serve_config(), seed=0)


# --------------------------------------------------------------- requests

def test_request_events_emitted_and_valid(eng, tmp_path):
    jsonl = str(tmp_path / "serve.jsonl")
    eng.reset()
    out = run_serve(eng, trace(), jsonl_path=jsonl, window_iters=3)
    assert schema.validate_jsonl(jsonl) == []
    events = [json.loads(l) for l in open(jsonl)]
    reqs = [e for e in events if e["schema"] == schema.REQUEST_SCHEMA_ID]
    results = {r.rid: r for r in out["results"]}
    assert len(reqs) == len(results) == 5
    assert out["summary"]["request_events"] == 5
    for e in reqs:
        r = results[e["rid"]]
        assert e["tokens_out"] == len(r.tokens)
        assert e["prompt_tokens"] == r.prompt_len
        assert e["finish_reason"] in ("eos", "length")
        assert e["queue_wait_ms"] >= 0
        assert e["prefill_ms"] > 0
        # the lifecycle adds up: submit -> admit -> first token
        assert e["ttft_ms"] == pytest.approx(
            e["queue_wait_ms"] + e["prefill_ms"], rel=0.05, abs=1.0)
        assert e["pages_mapped"] >= 1
        assert e["prefix_hit"] is False       # prompts < one page
    serves = [e for e in events if e["schema"] == schema.SERVE_SCHEMA_ID]
    assert all(e["version"] == 3 for e in serves)
    # windows account for every completion exactly once
    assert sum(e["requests_completed"] for e in serves) == 5


def test_request_events_opt_out(eng, tmp_path):
    jsonl = str(tmp_path / "serve.jsonl")
    eng.reset()
    tel = ServeTelemetry(eng, jsonl_path=jsonl, window_iters=4,
                         request_events=False)
    sched = ContinuousScheduler(eng, on_event=tel.on_iteration,
                                on_complete=tel.on_complete)
    sched.run(trace(3))
    tel.flush(sched)
    tel.close()
    events = [json.loads(l) for l in open(jsonl)]
    assert not [e for e in events
                if e["schema"] == schema.REQUEST_SCHEMA_ID]
    assert tel.request_events_emitted == 0


def test_serve_window_v3_gauges(eng, tmp_path):
    jsonl = str(tmp_path / "serve.jsonl")
    eng.reset()
    run_serve(eng, trace(4), jsonl_path=jsonl, window_iters=2)
    serves = [json.loads(l) for l in open(jsonl)]
    serves = [e for e in serves if e["schema"] == schema.SERVE_SCHEMA_ID]
    assert serves
    pool = eng.cache_spec.num_pages
    for e in serves:
        assert 0 <= e["slots_in_use"] <= e["slots"]
        assert 0 <= e["free_pages"] <= pool
        assert e["lru_pages"] >= 0 and e["shared_pages"] >= 0
        assert e["admission_refusals"] == 0
        # the serve detector counters ride the event's counter roll-up
        assert "serve_admission_starvation" in e["counters"]
    # mid-run windows saw occupied slots
    assert max(e["slots_in_use"] for e in serves) >= 1 \
        or max(e["active_slots_mean"] for e in serves) > 0


# ----------------------------------------------- per-request percentiles

def _result(rid, itl_s, ttft_s=0.01, queue_wait_s=0.002):
    return RequestResult(
        rid=rid, tokens=list(range(len(itl_s) + 1)),
        finish_reason="length", ttft_s=ttft_s, itl_s=list(itl_s),
        prompt_len=4, queue_wait_s=queue_wait_s, prefill_s=0.008,
        finished_ts=0.0, slot=0)


def test_summary_percentiles_are_per_request():
    """The documented D>1 failure: tokens arrive in bursts, so D-1 of
    every D pooled per-token gaps are exactly 0 and the pooled p50 reads
    0.  Per-request mean-ITL samples keep the percentile meaningful."""
    # 8 requests, each decoded in D=4 bursts: gaps [0, 0, 0, 40ms] x 2
    results = [_result(i, [0.0, 0.0, 0.0, 0.04] * 2) for i in range(8)]
    s = latency_summary(results, elapsed_s=1.0)
    # pooled per-token p50 would be 0.0 — the per-request p50 is the
    # mean gap, 10 ms
    assert s["itl_p50_ms"] == pytest.approx(10.0)
    assert s["itl_p99_ms"] == pytest.approx(10.0)
    # the pooled mean survives as the cross-D number
    assert s["itl_mean_ms"] == pytest.approx(10.0)
    assert s["queue_wait_p50_ms"] == pytest.approx(2.0)
    assert s["queue_wait_p99_ms"] == pytest.approx(2.0)


def test_summary_handles_single_token_requests():
    results = [_result(0, []), _result(1, [0.02, 0.02])]
    s = latency_summary(results, elapsed_s=1.0)
    # the one-token request contributes no ITL sample, but keeps its
    # TTFT/queue-wait samples
    assert s["itl_p50_ms"] == pytest.approx(20.0)
    assert s["requests"] == 2
    empty = latency_summary([], elapsed_s=0.0)
    assert empty["itl_p50_ms"] is None
    assert empty["queue_wait_p99_ms"] is None


# ------------------------------------------------------ schema evolution

def _serve_event_v(version):
    base = {
        "schema": schema.SERVE_SCHEMA_ID, "version": version, "ts": 1.0,
        "window": 1, "decode_iters": 4, "tokens_out": 9, "admitted": 2,
        "evicted": 1, "active_slots_mean": 1.5, "queue_depth": 0,
        "slots": 4, "kv_cache_gb": 0.01, "tokens_per_sec": 100.0,
        "ttft_p50_ms": 5.0, "ttft_p99_ms": 9.0, "itl_p50_ms": 1.0,
        "itl_p99_ms": 2.0, "counters": {},
    }
    if version >= 2:
        base.update({"prefix_hits": 0, "prefix_tokens_reused": 0,
                     "spec_proposed": 0, "spec_accepted": 0})
    if version >= 3:
        base.update({"requests_completed": 1, "queue_wait_p50_ms": 0.5,
                     "queue_wait_p99_ms": 0.9, "itl_mean_ms": 1.1,
                     "slots_in_use": 2, "free_pages": 3, "lru_pages": 0,
                     "shared_pages": 0, "admission_refusals": 0})
    return base


def _request_event(**over):
    e = {
        "schema": schema.REQUEST_SCHEMA_ID, "version": 1, "ts": 1.0,
        "rid": 0, "slot": 1, "prompt_tokens": 4, "tokens_out": 3,
        "finish_reason": "length", "queue_wait_ms": 0.5,
        "prefill_ms": 2.0, "ttft_ms": 2.5, "decode_ms": 4.0,
        "itl_mean_ms": 2.0, "itl_max_ms": 3.0, "prefix_hit": False,
        "prefix_tokens_reused": 0, "pages_mapped": 1,
    }
    e.update(over)
    return e


def test_serve_v1_v2_logs_still_validate():
    assert schema.validate_any(_serve_event_v(1)) is None
    assert schema.validate_any(_serve_event_v(2)) is None
    assert schema.validate_any(_serve_event_v(3)) is None
    # v3 requires the new columns; v1/v2 must not
    bad = _serve_event_v(3)
    del bad["slots_in_use"]
    assert "slots_in_use" in schema.validate_any(bad)
    bad = _serve_event_v(3)
    bad["slots_in_use"] = 9            # > slots
    assert "slots_in_use" in schema.validate_any(bad)


def test_request_event_schema_negatives():
    assert schema.validate_any(_request_event()) is None
    assert "finish_reason" in schema.validate_any(
        _request_event(finish_reason="timeout"))
    assert "tokens_out" in schema.validate_any(
        _request_event(tokens_out=0))
    assert "prefix_tokens_reused" in schema.validate_any(
        _request_event(prefix_tokens_reused=99))
    bad = _request_event()
    del bad["pages_mapped"]
    assert "pages_mapped" in schema.validate_any(bad)
    # unmeasured latency columns are null, not missing
    assert schema.validate_any(
        _request_event(itl_mean_ms=None, decode_ms=None)) is None


def test_validator_cli_mixed_serve_stream(tmp_path):
    """Mixed v1/v2/v3 serve + request + startup stream: validator-clean
    with a version-aware summary; unknown schema stays exit 2."""
    good = tmp_path / "mixed.jsonl"
    startup = {"schema": schema.STARTUP_SCHEMA_ID, "version": 2,
               "ts": 1.0, "rank": 0, "host": "h", "step": 0,
               "time_to_first_step_s": 1.0, "first_dispatch_s": 0.5,
               "restore_seconds": 0.1, "compile_cache_hits": 0,
               "compile_cache_misses": 2}
    events = [startup, _serve_event_v(1), _serve_event_v(2),
              _serve_event_v(3), _request_event()]
    good.write_text("".join(json.dumps(e) + "\n" for e in events))
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.observability", str(good)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "request" in proc.stdout and "serve" in proc.stdout

    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"schema": "dstpu.telemetry.bogus",
                               "version": 1}) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.observability", str(bad)],
        capture_output=True, text=True)
    assert proc.returncode == 2
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.observability", str(empty)],
        capture_output=True, text=True)
    assert proc.returncode == 2


# ------------------------------------------------------- live endpoints

def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.getcode(), r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_health_endpoints_mid_serve(tmp_path):
    cfg = serve_config(obs={"watchdog_timeout_s": 30.0,
                            "window_iters": 2})
    engine = InferenceEngine(tiny_model(), config=cfg, seed=0)
    obs = ServeObservability(engine)
    assert obs.watchdog is not None and engine.watchdog is obs.watchdog
    obs.health = HealthServer(0, obs)      # OS-assigned test port
    try:
        tel = ServeTelemetry(engine,
                             jsonl_path=str(tmp_path / "s.jsonl"),
                             window_iters=2, observability=obs)
        obs.telemetry = tel
        sched = ContinuousScheduler(engine, on_event=tel.on_iteration,
                                    on_complete=tel.on_complete)
        obs.note_scheduler(sched)
        for r in trace(4, seed=5):
            sched.submit(r)
        for _ in range(3):                 # mid-serve: slots occupied
            tel.on_iteration(sched, sched.step())
        assert sched.active >= 1
        code, body = _get(obs.port, "/healthz")
        assert code == 200 and json.loads(body)["ok"] is True
        code, body = _get(obs.port, "/status")
        status = json.loads(body)
        assert code == 200
        assert status["slots_in_use"] >= 1
        assert status["pool"]["pages_in_use"] >= 1
        assert status["healthy"] is True
        code, text = _get(obs.port, "/metrics")
        assert code == 200
        parsed = parse_prometheus_text(text)     # the CI parse gate
        assert parsed["dstpu_healthy"] == 1
        assert parsed["dstpu_slots_in_use"] >= 1
        assert parsed["dstpu_pool_pages_in_use"] >= 1
        assert parsed["dstpu_slots_total"] == engine.num_slots
        # drain and check the window-derived gauges appear
        while sched.queue or sched.active:
            tel.on_iteration(sched, sched.step())
        tel.flush(sched)
        tel.close()
        _, text = _get(obs.port, "/metrics")
        parsed = parse_prometheus_text(text)
        assert parsed["dstpu_requests_completed"] == 4
        assert "dstpu_window_tokens_per_sec" in parsed
        assert "dstpu_window_queue_wait_p99_ms" in parsed
    finally:
        obs.close()


def test_health_endpoints_from_config_port(tmp_path):
    """inference.observability.health_port (and the env fallback) wires
    the server up through run_serve without any explicit driver."""
    port = int(os.environ.get("DSTPU_TEST_SERVE_PORT", "8965"))
    cfg = serve_config(obs={"health_port": port})
    engine = InferenceEngine(tiny_model(), config=cfg, seed=0)
    assert observability.configured(engine.config)
    obs = ServeObservability(engine)
    try:
        assert obs.port == port          # + process_index 0
        code, _ = _get(obs.port, "/healthz")
        assert code == 200
    finally:
        obs.close()


# ------------------------------------------------- hang capture + chaos

def test_stalled_decode_watchdog_503_dump(tmp_path):
    """The CI chaos leg's contract, in-process: a stalled decode fires
    the serve watchdog, /healthz flips to 503, the dump is loadable and
    names the stalled decode dispatch — and the outputs still match a
    clean run (a stall is wall-clock, not numerics)."""
    reqs = trace(3, seed=9)
    clean = InferenceEngine(tiny_model(), config=serve_config(), seed=0)
    clean_out = run_serve(clean, [Request(rid=r.rid,
                                          prompt=list(r.prompt),
                                          max_new_tokens=r.max_new_tokens)
                                  for r in reqs])
    clean_tokens = {r.rid: r.tokens for r in clean_out["results"]}

    flightrec.RECORDER.configure(dump_dir=str(tmp_path))
    chaos.configure(stall_step=2, stall_s=30.0)
    cfg = serve_config(obs={"watchdog_timeout_s": 0.3,
                            "flight_recorder_dir": str(tmp_path)})
    engine = InferenceEngine(tiny_model(), config=cfg, seed=0)
    obs = ServeObservability(engine)
    obs.health = HealthServer(0, obs)
    try:
        # the stall ends when the watchdog reacted (wired by the driver)
        assert chaos._state.stall_until is obs.watchdog.fire_event
        out = run_serve(engine, reqs, observability=obs)
        assert obs.watchdog.fired
        assert not obs.healthy()
        code, body = _get(obs.port, "/healthz")
        assert code == 503 and json.loads(body)["ok"] is False
        _, text = _get(obs.port, "/metrics")
        assert parse_prometheus_text(text)["dstpu_healthy"] == 0
        path = os.path.join(str(tmp_path),
                            "flightrec_rank0_watchdog.json")
        payload = flightrec.load_dump(path)
        kinds = [e.get("kind") for e in payload["entries"]]
        assert any(str(k).startswith("serve_decode") for k in kinds)
        # the hang changed nothing about the tokens
        assert {r.rid: r.tokens for r in out["results"]} == clean_tokens
    finally:
        obs.close()


def test_serve_crash_dumps_flight_recorder(tmp_path):
    """Satellite: the serving driver's crash exit rides the same dump
    hook as the training driver's — a mid-drain exception leaves a
    loadable ``flightrec_rank<r>_crash.json``."""
    flightrec.RECORDER.configure(dump_dir=str(tmp_path))
    engine = InferenceEngine(tiny_model(), config=serve_config(), seed=0)
    calls = []

    def exploding_sampler(row):
        calls.append(1)
        if len(calls) >= 3:
            raise RuntimeError("boom mid-drain")
        return int(np.argmax(row))

    with pytest.raises(RuntimeError, match="boom mid-drain"):
        run_serve(engine, trace(3, seed=11), sampler=exploding_sampler)
    payload = flightrec.load_dump(
        os.path.join(str(tmp_path), "flightrec_rank0_crash.json"))
    crash = [e for e in payload["entries"] if e["kind"] == "crash"]
    assert crash and crash[-1]["where"] == "serve"


def test_flight_recorder_dir_wins_without_driver(tmp_path):
    """A configured ``flight_recorder_dir`` must place serve
    post-mortems even when NO ServeObservability is built (no health
    port, no watchdog) and the JSONL log lives elsewhere — the one
    shared resolver in inference/observability.py."""
    dumps = tmp_path / "dumps"
    logs = tmp_path / "logs"
    logs.mkdir()
    flightrec.RECORDER.configure(dump_dir=None)
    cfg = serve_config(obs={"flight_recorder_dir": str(dumps),
                            "jsonl_path": str(logs / "serve.jsonl")})
    engine = InferenceEngine(tiny_model(), config=cfg, seed=0)
    assert not observability.configured(engine.config)

    def boom(row):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        run_serve(engine, trace(2, seed=13), sampler=boom)
    payload = flightrec.load_dump(
        os.path.join(str(dumps), "flightrec_rank0_crash.json"))
    assert payload["reason"] == "crash"


# -------------------------------------------------- trajectory neutrality

def test_observability_trajectory_neutral(tmp_path):
    """Greedy outputs AND the deliberate-fence count are identical with
    the full stack on (request events + JSONL + watchdog + detectors)
    vs everything off — the acceptance contract, and what keeps the
    dispatch-cost pass's FENCE_COUNT prediction exact either way."""
    reqs = trace(6, seed=21)

    def clone():
        return [Request(rid=r.rid, prompt=list(r.prompt),
                        max_new_tokens=r.max_new_tokens) for r in reqs]

    plain = InferenceEngine(tiny_model(), config=serve_config(), seed=0)
    f0 = fences.FENCE_COUNT
    base = run_serve(plain, clone())
    base_fences = fences.FENCE_COUNT - f0

    cfg = serve_config(obs={"watchdog_timeout_s": 30.0,
                            "window_iters": 2})
    engine = InferenceEngine(tiny_model(), config=cfg, seed=0)
    f0 = fences.FENCE_COUNT
    obs_out = run_serve(engine, clone(),
                        jsonl_path=str(tmp_path / "s.jsonl"),
                        window_iters=2)
    obs_fences = fences.FENCE_COUNT - f0

    assert ({r.rid: r.tokens for r in obs_out["results"]}
            == {r.rid: r.tokens for r in base["results"]})
    assert obs_fences == base_fences
    # and the dispatch plan's prediction still matches reality: the
    # observability stack added zero executables to the promised set
    pred = engine.predict_executables()
    assert pred.total == plain.predict_executables().total


# ------------------------------------------------------------- detectors

def test_detector_admission_starvation():
    det = detectors.ServeAnomalyDetector(starvation_windows=1)
    before = detectors.SERVE_COUNTERS.serve_admission_starvation
    out = det.check_window(queue_depth=3, admitted=0, refusals_delta=2,
                           spec_proposed_delta=0, spec_accepted_delta=0,
                           lru_reclaims_delta=0, prefix_hits_delta=0)
    assert out == ["admission_starvation"]
    assert detectors.SERVE_COUNTERS.serve_admission_starvation \
        == before + 1
    # progress resets the streak: admitted > 0 never flags
    out = det.check_window(queue_depth=3, admitted=1, refusals_delta=2,
                           spec_proposed_delta=0, spec_accepted_delta=0,
                           lru_reclaims_delta=0, prefix_hits_delta=0)
    assert out == []
    # a 2-window threshold needs 2 consecutive starved windows
    det2 = detectors.ServeAnomalyDetector(starvation_windows=2)
    assert det2.check_window(
        queue_depth=1, admitted=0, refusals_delta=1,
        spec_proposed_delta=0, spec_accepted_delta=0,
        lru_reclaims_delta=0, prefix_hits_delta=0) == []
    assert det2.check_window(
        queue_depth=1, admitted=0, refusals_delta=1,
        spec_proposed_delta=0, spec_accepted_delta=0,
        lru_reclaims_delta=0,
        prefix_hits_delta=0) == ["admission_starvation"]


def test_detector_accept_rate_collapse():
    det = detectors.ServeAnomalyDetector(accept_floor=0.25,
                                         min_spec_proposals=16)
    ok = dict(queue_depth=0, admitted=1, refusals_delta=0,
              lru_reclaims_delta=0, prefix_hits_delta=0)
    # healthy accept rate: quiet
    assert det.check_window(spec_proposed_delta=20,
                            spec_accepted_delta=15, **ok) == []
    # too few proposals to judge: quiet
    assert det.check_window(spec_proposed_delta=4,
                            spec_accepted_delta=0, **ok) == []
    # collapse
    assert det.check_window(
        spec_proposed_delta=20, spec_accepted_delta=2,
        **ok) == ["spec_accept_collapse"]
    assert detectors.SERVE_COUNTERS.serve_accept_collapse == 1


def test_detector_pool_thrash():
    det = detectors.ServeAnomalyDetector(thrash_reclaims=8)
    ok = dict(queue_depth=0, admitted=1, refusals_delta=0,
              spec_proposed_delta=0, spec_accepted_delta=0)
    # reclaims below the floor: quiet
    assert det.check_window(lru_reclaims_delta=4, prefix_hits_delta=0,
                            **ok) == []
    # heavy reclaim but the cache still pays for itself: quiet
    assert det.check_window(lru_reclaims_delta=10, prefix_hits_delta=12,
                            **ok) == []
    assert det.check_window(lru_reclaims_delta=10, prefix_hits_delta=1,
                            **ok) == ["pool_thrash"]
    assert detectors.SERVE_COUNTERS.serve_pool_thrash == 1


# ------------------------------------------------------------ pool gauges

def test_page_pool_gauges_shared_and_lru():
    import jax.numpy as jnp
    spec = kvcache.KVCacheSpec(layers=1, slots=2, capacity=32,
                               kv_heads_local=1, head_dim=4,
                               dtype=jnp.float32, page_tokens=8)
    pool = kvcache.PagePool(spec)
    # two full pages + one token: lookup leaves at least one token to
    # forward, so both full pages are reusable
    prompt = list(range(17))
    g0 = pool.admit(0, prompt, 4)
    pool.publish(g0)
    g1 = pool.admit(1, prompt, 4)      # hits the published chain
    assert g1.reused_pages == 2
    g = pool.gauges()
    assert g["shared_pages"] == 2      # refcount 2 on the shared pages
    assert g["prefix_hits"] == 1
    assert g["prefix_tokens_reused"] == 16
    assert g["pages_in_use"] == g0.new_pages + g1.new_pages
    pool.release(0)
    pool.release(1)
    g = pool.gauges()
    assert g["pages_in_use"] == 0
    assert g["lru_pages"] == 2         # published pages park on the LRU
    assert g["free_pages"] == spec.num_pages
    # reclaiming the LRU pages counts (the thrash signal)
    while pool._free:
        pool._free.pop()
    assert pool._take_page() is not None
    assert pool.gauges()["lru_reclaims"] == 1


# ---------------------------------------------------------- config guards

def test_config_guards():
    with pytest.raises(DeepSpeedConfigError, match="unknown"):
        InferenceEngine(tiny_model(),
                        config=serve_config(obs={"bogus": 1}), seed=0)
    for bad in ({"window_iters": 0}, {"watchdog_timeout_s": -1},
                {"health_port": 99999}, {"accept_floor": 1.5},
                {"thrash_reclaims": -2}, {"jsonl_path": 7}):
        with pytest.raises(DeepSpeedConfigError):
            InferenceEngine(tiny_model(), config=serve_config(obs=bad),
                            seed=0)
